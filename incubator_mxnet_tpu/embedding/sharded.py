"""Sharded sparse-embedding table client.

One `ShardedEmbedding` names a logical table of ``num_rows x dim`` that
NEVER materializes densely: its rows are range- or hash-partitioned into
row shards, each hosted by one `dist.server.ParameterServer` process
(the `embed_init`/`embed_push`/`embed_pull` commands over the existing
seq-numbered at-most-once transport).  Training pushes row-sparse grads
to the owning shards, where `optimizer.py`'s lazy SGD/Adam paths update
only the touched rows; lookups ride the device-resident `HotRowCache`
so hot ids gather straight from HBM.

Failure semantics mirror the dense dist kvstore (`dist/kvstore_dist.py`):
each shard has its own `CircuitBreaker`; a tripped breaker — or a shard
that answers but forgot a table this client initialized (restarted
empty) — becomes a structured `ServerLostError` naming the shard, its
address, and the row range it owned.  `replace_shard` re-attaches a
respawned server and restores its rows, the chaos-certified recovery.
"""
from __future__ import annotations

import threading
import time

import numpy as np

from .. import config as _config
from ..analysis import locks as _locks
from ..base import MXNetError
from ..dist.transport import Channel
from ..obs import metrics as _obs_metrics, trace as _trace
from ..resilience import CircuitBreaker, ServerLostError, faults as _faults
from .cache import HotRowCache

# splitmix64 finalizer: a stable, vectorizable integer mix so hash
# partitioning spreads sequential hot ids across shards
_MIX1 = np.uint64(0xBF58476D1CE4E5B9)
_MIX2 = np.uint64(0x94D049BB133111EB)


def _mix64(ids):
    x = np.asarray(ids, dtype=np.uint64)
    x = (x ^ (x >> np.uint64(30))) * _MIX1
    x = (x ^ (x >> np.uint64(27))) * _MIX2
    return x ^ (x >> np.uint64(31))


def shard_of_ids(ids, num_rows, num_shards, partition="range"):
    """Owning shard per id (np int array -> np int array).

    'range': shard s owns the contiguous interval
    ``[num_rows*s//n, num_rows*(s+1)//n)`` (ps-lite value ranges —
    locality-preserving, one searchsorted).  'hash': splitmix64 mix
    modulo shards (skew-resistant for power-law id traffic)."""
    ids = np.asarray(ids, dtype=np.int64)
    if partition == "hash":
        return (_mix64(ids) % np.uint64(num_shards)).astype(np.int64)
    bounds = np.array([num_rows * s // num_shards
                       for s in range(1, num_shards)], dtype=np.int64)
    return np.searchsorted(bounds, ids, side="right")


class ShardedEmbedding:
    """A row-sharded embedding table hosted on parameter servers."""

    def __init__(self, name, num_rows, dim, servers, dtype="float32",
                 partition=None, seed=0, scale=0.01, cache_rows=None,
                 optimizer=None, init_values=None):
        self.name = str(name)
        self.num_rows = int(num_rows)
        self.dim = int(dim)
        self.dtype = np.dtype(dtype)
        self.partition = partition or str(
            _config.get("MXNET_EMBED_PARTITION"))
        if self.partition not in ("range", "hash"):
            raise MXNetError(
                f"ShardedEmbedding({self.name!r}): unknown partition "
                f"{self.partition!r} (one of 'range', 'hash')")
        self._seed, self._scale = int(seed), float(scale)
        self._lock = threading.RLock()
        self._chans = [c if isinstance(c, Channel) else Channel(*c)
                       for c in servers]
        if not self._chans:
            raise MXNetError(
                f"ShardedEmbedding({self.name!r}): at least one shard "
                "server is required")
        self.num_shards = len(self._chans)
        # one request lock per shard: a Channel is a single framed TCP
        # stream — concurrent callers (serving threads) must not
        # interleave frames or steal each other's replies
        self._shard_locks = [_locks.make_lock("embedding.shard")
                             for _ in self._chans]
        self._breakers = [
            CircuitBreaker(
                failure_threshold=int(_config.get(
                    "MXNET_EMBED_BREAKER_THRESHOLD")),
                reset_timeout=float(_config.get(
                    "MXNET_EMBED_BREAKER_RESET_S")))
            for _ in self._chans]
        # guard: a table this tier exists to shard must never densify
        # onto one device — the modeled single-device budget is the gate
        budget = int(_config.get("MXNET_EMBED_HBM_BUDGET_MB")) * (1 << 20)
        self.table_bytes = self.num_rows * self.dim * self.dtype.itemsize
        self.over_hbm_ratio = self.table_bytes / max(budget, 1)
        cache_rows = int(_config.get("MXNET_EMBED_CACHE_ROWS")) \
            if cache_rows is None else int(cache_rows)
        self.cache = HotRowCache(self.dim, cache_rows, self.dtype,
                                 name=self.name) if cache_rows > 0 else None
        self._inited = False
        self._opt_blob = None
        # per-shard wire counters (the `embedding.*` obs namespace)
        self._pushed = [0] * self.num_shards
        self._pulled = [0] * self.num_shards
        self.lookups = 0
        self.lookup_rows = 0
        self.failovers = 0
        self._t0 = time.monotonic()
        _obs_metrics.register_producer(f"embedding.{self.name}",
                                       self.stats)
        self._init_shards(init_values)
        if optimizer is not None:
            self.set_optimizer(optimizer)

    # -- partition ------------------------------------------------------------
    def _range_of(self, shard):
        lo = self.num_rows * shard // self.num_shards
        hi = self.num_rows * (shard + 1) // self.num_shards
        return lo, hi

    def _owned_desc(self, shard):
        """What the shard owns, for ServerLostError evidence."""
        if self.partition == "range":
            lo, hi = self._range_of(shard)
            return [f"{self.name}[{lo}:{hi}]"]
        return [f"{self.name}[hash shard {shard}/{self.num_shards}]"]

    def shard_of(self, ids):
        return shard_of_ids(ids, self.num_rows, self.num_shards,
                            self.partition)

    # -- transport ------------------------------------------------------------
    def _request(self, shard, msg):
        """One shard round trip with the dist failover semantics: the
        channel retries transient failures; exhausted attempts count
        against the shard's breaker; a tripped breaker (or a shard that
        restarted empty) raises `ServerLostError` naming the shard and
        the rows it owned."""
        with self._shard_locks[shard]:
            chan = self._chans[shard]
            breaker = self._breakers[shard]
            addr = f"{chan.host}:{chan.port}"
            if not breaker.allow():
                raise ServerLostError(
                    shard, addr, keys=self._owned_desc(shard),
                    reason=f"circuit breaker is {breaker.state} after "
                           f"{breaker.failure_threshold} consecutive "
                           "failures")
            framed = False
            while True:
                try:
                    reply = chan.resend_last() if framed \
                        else chan.request(msg)
                    break
                except TimeoutError as e:
                    framed = True
                    if breaker.record_failure():
                        raise ServerLostError(
                            shard, addr, keys=self._owned_desc(shard),
                            reason=f"unresponsive during "
                                   f"{msg.get('cmd')!r}: "
                                   f"{breaker.failure_threshold} "
                                   f"consecutive timeouts ({e})") from e
                    _faults.note("retry", site="embedding", shard=shard,
                                 cmd=msg.get("cmd"), error="timeout")
                except (ConnectionError, EOFError, OSError) as e:
                    framed = True
                    if breaker.record_failure():
                        raise ServerLostError(
                            shard, addr, keys=self._owned_desc(shard),
                            reason=f"unreachable during "
                                   f"{msg.get('cmd')!r} after "
                                   f"{breaker.failure_threshold} "
                                   f"consecutive failures "
                                   f"({type(e).__name__}: {e})") from e
                    _faults.note("reconnect", site="embedding",
                                 shard=shard, cmd=msg.get("cmd"))
        if "error" in reply:
            err = reply["error"]
            if "has not been initialized" in err and self._inited:
                # the shard answered but forgot a table this client DID
                # initialize: it restarted empty — its rows are gone
                breaker.record_failure()
                raise ServerLostError(
                    shard, addr, keys=self._owned_desc(shard),
                    reason=f"server restarted without state ({err})")
            breaker.record_success()
            raise MXNetError(err)
        breaker.record_success()
        return reply

    # -- init / optimizer -----------------------------------------------------
    def _init_shards(self, init_values):
        for s in range(self.num_shards):
            msg = {"cmd": "embed_init", "table": self.name,
                   "dim": self.dim, "dtype": self.dtype.name,
                   "seed": self._seed, "scale": self._scale}
            if self.partition == "range":
                lo, hi = self._range_of(s)
                msg["row_start"], msg["row_end"] = lo, hi
                if init_values is not None:
                    msg["values"] = np.asarray(init_values[lo:hi],
                                               dtype=self.dtype)
            else:
                ids = np.arange(self.num_rows, dtype=np.int64)
                ids = ids[self.shard_of(ids) == s]
                msg["ids"] = ids
                if init_values is not None:
                    msg["values"] = np.asarray(init_values,
                                               dtype=self.dtype)[ids]
            self._request(s, msg)
        self._inited = True

    def set_optimizer(self, optimizer):
        """Ship the optimizer to every shard server; pushes then apply
        the lazy row-sparse update shard-side (only touched rows)."""
        import pickle
        blob = pickle.dumps(optimizer)
        self._opt_blob = blob    # re-shipped by replace_shard
        for s in range(self.num_shards):
            self._request(s, {"cmd": "set_optimizer", "optimizer": blob})

    # -- data path ------------------------------------------------------------
    def _group_by_shard(self, ids):
        shards = self.shard_of(ids)
        for s in np.unique(shards):
            yield int(s), np.nonzero(shards == s)[0]

    def pull_rows(self, ids):
        """Rows for unique ``ids`` straight from the shards (cache
        bypassed) as np [len(ids), dim]."""
        ids = np.asarray(ids, dtype=np.int64).ravel()
        out = np.empty((len(ids), self.dim), dtype=self.dtype)
        for s, at in self._group_by_shard(ids):
            reply = self._request(s, {"cmd": "embed_pull",
                                      "table": self.name,
                                      "ids": ids[at]})
            out[at] = np.asarray(reply["values"], dtype=self.dtype)
            self._pulled[s] += len(at)
        return out

    def lookup(self, ids, out_np=False):
        """Embedding vectors for ``ids`` (any shape) as a device array
        of shape ``ids.shape + (dim,)`` (np array when ``out_np``).

        Hot ids gather from the device cache; cold ids pull from their
        shards in one batch per shard and are pinned for next time."""
        ids = np.asarray(ids, dtype=np.int64)
        flat = ids.ravel()
        with _trace.span("embedding.lookup", cat="embedding",
                         table=self.name, rows=int(flat.size)):
            if self.cache is not None:
                rows, _h, _m = self.cache.lookup(flat, self.pull_rows)
            else:
                rows = self.pull_rows(flat)
            with self._lock:
                self.lookups += 1
                self.lookup_rows += int(flat.size)
        if out_np:
            return np.asarray(rows).reshape(ids.shape + (self.dim,))
        if isinstance(rows, np.ndarray):   # cache disabled: densify once
            import jax.numpy as jnp
            rows = jnp.asarray(rows)
        return rows.reshape(ids.shape + (self.dim,))

    def push_grad(self, ids, grads):
        """Push a row-sparse gradient: duplicate ids pre-sum, each
        shard receives only the rows it owns, the lazy optimizer updates
        them server-side, and the cached copies are invalidated."""
        from ..ndarray.sparse import aggregate_row_sparse
        ids = np.asarray(ids, dtype=np.int64).ravel()
        grads = np.asarray(grads, dtype=self.dtype).reshape(len(ids),
                                                            self.dim)
        uniq, summed = aggregate_row_sparse(ids, grads)
        for s, at in self._group_by_shard(uniq):
            reply = self._request(
                s, {"cmd": "embed_push", "table": self.name,
                    "ids": uniq[at], "values": summed[at]})
            self._pushed[s] += len(at)
            if self.cache is not None:
                # the reply carries the post-update rows: refresh the
                # resident copies in place so hot rows stay hot across
                # training steps (invalidation would force a re-pull)
                self.cache.refresh(uniq[at], reply["values"])

    def assign_rows(self, ids, values):
        """Overwrite rows (checkpoint restore / weight swap)."""
        ids = np.asarray(ids, dtype=np.int64).ravel()
        values = np.asarray(values, dtype=self.dtype).reshape(
            len(ids), self.dim)
        for s, at in self._group_by_shard(ids):
            self._request(s, {"cmd": "embed_push", "table": self.name,
                              "ids": ids[at], "values": values[at],
                              "op": "assign"})
            self._pushed[s] += len(at)
        if self.cache is not None:
            self.cache.invalidate(ids)

    # -- checkpoint / recovery ------------------------------------------------
    def checkpoint_rows(self):
        """The full table streamed back chunk-by-chunk as np
        [num_rows, dim] — host-resident only, for the checkpoint plane
        (one reply never carries a table-sized frame)."""
        chunk = int(_config.get("MXNET_EMBED_PULL_CHUNK"))
        out = np.empty((self.num_rows, self.dim), dtype=self.dtype)
        for lo in range(0, self.num_rows, chunk):
            ids = np.arange(lo, min(lo + chunk, self.num_rows),
                            dtype=np.int64)
            out[lo:lo + len(ids)] = self.pull_rows(ids)
        return out

    def restore_rows(self, table):
        """Push a checkpointed table back out to the shards."""
        table = np.asarray(table, dtype=self.dtype)
        if table.shape != (self.num_rows, self.dim):
            raise MXNetError(
                f"restore_rows({self.name!r}): checkpoint shape "
                f"{table.shape} != table shape "
                f"{(self.num_rows, self.dim)}")
        chunk = int(_config.get("MXNET_EMBED_PULL_CHUNK"))
        for lo in range(0, self.num_rows, chunk):
            ids = np.arange(lo, min(lo + chunk, self.num_rows),
                            dtype=np.int64)
            self.assign_rows(ids, table[lo:lo + len(ids)])

    def replace_shard(self, shard, host, port, restore=None):
        """Re-attach a respawned shard server: reconnect the channel,
        reset its breaker, re-init the shard's rows (from ``restore``, a
        full-table np array, when given — else the seeded init), and
        drop every cached row it owns.  The chaos-certified recovery."""
        with self._lock:
            try:
                self._chans[shard].close()
            except Exception:
                pass
            self._chans[shard] = Channel(host, int(port))
            self._breakers[shard] = CircuitBreaker(
                failure_threshold=int(_config.get(
                    "MXNET_EMBED_BREAKER_THRESHOLD")),
                reset_timeout=float(_config.get(
                    "MXNET_EMBED_BREAKER_RESET_S")))
            self.failovers += 1
        msg = {"cmd": "embed_init", "table": self.name, "dim": self.dim,
               "dtype": self.dtype.name, "seed": self._seed,
               "scale": self._scale}
        if self.partition == "range":
            lo, hi = self._range_of(shard)
            msg["row_start"], msg["row_end"] = lo, hi
            owned = np.arange(lo, hi, dtype=np.int64)
        else:
            owned = np.arange(self.num_rows, dtype=np.int64)
            owned = owned[self.shard_of(owned) == shard]
            msg["ids"] = owned
        if restore is not None:
            msg["values"] = np.asarray(restore, dtype=self.dtype)[owned]
        self._request(shard, msg)
        if getattr(self, "_opt_blob", None) is not None:
            # the respawned server starts without an updater: re-ship
            # the optimizer or the next grad push is a structured error
            self._request(shard, {"cmd": "set_optimizer",
                                  "optimizer": self._opt_blob})
        if self.cache is not None:
            self.cache.invalidate(owned)

    # -- obs ------------------------------------------------------------------
    def stats(self):
        dt = max(time.monotonic() - self._t0, 1e-9)
        out = {
            "table": self.name, "num_rows": self.num_rows,
            "dim": self.dim, "num_shards": self.num_shards,
            "partition": self.partition,
            "table_bytes": self.table_bytes,
            "over_hbm_ratio": round(self.over_hbm_ratio, 3),
            "lookups": self.lookups, "lookup_rows": self.lookup_rows,
            "lookup_qps": round(self.lookups / dt, 3),
            "failovers": self.failovers,
            # dict (not list) so metrics.flatten keeps the per-shard
            # counters in the embedding.* scrape
            "shards": {
                str(s): {"addr": f"{c.host}:{c.port}",
                         "rows_pushed": self._pushed[s],
                         "rows_pulled": self._pulled[s],
                         "breaker": b.state}
                for s, (c, b) in enumerate(zip(self._chans,
                                               self._breakers))},
        }
        if self.cache is not None:
            out["cache"] = self.cache.stats()
        return out

    def close(self):
        for c in self._chans:
            try:
                c.close()
            except Exception:
                pass
