"""Device-resident hot-row cache for sharded embedding tables.

A recommender's id traffic is power-law: a small hot set covers most
lookups.  The cache pins up to `capacity` rows in ONE device buffer
``(capacity, dim)`` and serves hits with a batched device gather — the
steady-state lookup for hot ids never leaves HBM and never touches the
parameter servers.  Misses are pulled from their shards in one batch,
scattered into LRU-evicted slots, then the whole request is gathered.

Program-cache discipline: the gather and the scatter are TWO
`cached_jit` programs.  The scatter donates the cache buffer (the old
buffer dies the moment the new one exists — no 2x cache HBM spike), and
both pad their id axis to the next power of two so the signature set is
O(log capacity) and the steady state (fixed batch, all hits) replays one
executable with ZERO recompiles — the run_embed_bench gate.
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as np

from .. import config as _config
from ..analysis import locks as _locks
from ..compile.program import cached_jit


def _pad_pow2(n):
    return 1 if n <= 1 else 1 << (int(n - 1).bit_length())


def _gather(buf, slots):
    return buf[slots]


def _scatter(buf, slots, rows):
    return buf.at[slots].set(rows)


class HotRowCache:
    """LRU over row ids; one device buffer, batched gather/scatter."""

    def __init__(self, dim, capacity=None, dtype="float32", name="embed"):
        if capacity is None:
            capacity = int(_config.get("MXNET_EMBED_CACHE_ROWS"))
        self.capacity = int(capacity)
        self.dim = int(dim)
        self.dtype = np.dtype(dtype)
        self._lock = _locks.make_lock("embedding.cache")
        # id -> slot, most-recently-used LAST (OrderedDict move_to_end)
        self._slot = OrderedDict()
        self._free = list(range(self.capacity))
        self._buf = None           # device (capacity, dim), built lazily
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self._gather = cached_jit(_gather, label=f"{name}.cache.gather")
        # donation: the pre-scatter buffer is dead the moment the updated
        # one exists — without it the fill path holds 2x cache HBM
        self._scatter = cached_jit(_scatter, donate_argnums=(0,),
                                   label=f"{name}.cache.scatter")

    # -- stats ----------------------------------------------------------------
    # scraped through the owning table's `embedding.<name>` producer
    # (ShardedEmbedding.stats() nests this dict under "cache")
    def stats(self):  # mxlint: disable=untracked-stats
        with self._lock:
            total = self.hits + self.misses
            return {"capacity": self.capacity, "rows": len(self._slot),
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions,
                    "hit_rate": self.hits / total if total else 0.0}

    def program_count(self):
        """Distinct compiled signatures across both cache programs (the
        zero-steady-state-recompile certification reads this)."""
        return (len(self._gather.signatures())
                + len(self._scatter.signatures()))

    # -- internals ------------------------------------------------------------
    def _ensure_buf(self):
        if self._buf is None:
            import jax.numpy as jnp
            self._buf = jnp.zeros((self.capacity, self.dim),
                                  dtype=self.dtype)

    def _take_slots(self, n):
        """Allocate n slots, evicting LRU rows as needed (lock held)."""
        slots = []
        while len(slots) < n:
            if self._free:
                slots.append(self._free.pop())
            else:
                _evicted_id, slot = self._slot.popitem(last=False)
                self.evictions += 1
                slots.append(slot)
        return slots

    # -- API ------------------------------------------------------------------
    def lookup(self, ids, pull_fn):
        """Rows for ``ids`` (np int array) as ONE device array [len, dim].

        Hits gather straight from the device buffer; the unique missing
        ids go through ``pull_fn(miss_ids) -> np [k, dim]`` (the sharded
        pull), are scattered into LRU slots, and the full request then
        gathers.  Returns (device_rows, n_hits, n_misses).  Under heavy
        cross-thread eviction churn the retry is bounded: after a few
        rounds the batch is served uncached (host rows straight from
        ``pull_fn``) rather than hammering the parameter servers."""
        ids = np.asarray(ids, dtype=np.int64).ravel()
        id_list = ids.tolist()
        uniq = list(dict.fromkeys(id_list))
        # guard on the WHOLE batch's distinct ids, not just the misses:
        # when the batch itself cannot fit, the insert would evict the
        # batch's own resident rows, the post-insert check would fail,
        # and the re-pull loop would never converge
        if len(uniq) > self.capacity:
            raise ValueError(
                f"hot-row cache capacity {self.capacity} cannot "
                f"hold the {len(uniq)} distinct rows of one "
                "lookup — raise MXNET_EMBED_CACHE_ROWS past the "
                "per-batch distinct id count")
        self._ensure_buf()
        for _attempt in range(8):
            with self._lock:
                miss_occ = [i for i in id_list if i not in self._slot]
                miss = list(dict.fromkeys(miss_occ))
                n_miss = len(miss_occ)
                n_hit = len(ids) - n_miss
                # pin this batch's resident rows at the MRU end BEFORE
                # the miss insert: its evictions then only ever take
                # rows outside this batch (capacity >= batch distinct)
                for i in id_list:
                    if i in self._slot:
                        self._slot.move_to_end(i)
            if miss:
                rows = np.asarray(
                    pull_fn(np.asarray(miss, dtype=np.int64)),
                    dtype=self.dtype)
                self.insert(miss, rows)
            with self._lock:
                if any(i not in self._slot for i in id_list):
                    continue   # a concurrent lookup evicted us: re-pull
                self.hits += n_hit
                self.misses += n_miss
                slots = np.fromiter((self._slot[i] for i in id_list),
                                    dtype=np.int32, count=len(ids))
                for i in id_list:
                    self._slot.move_to_end(i)
                # dispatch the gather UNDER the lock: a concurrent
                # insert donates self._buf away, so the validated slots
                # and the buffer they index must be captured atomically
                # or the gather can read re-scattered rows
                return self._gathered(slots, len(ids)), n_hit, n_miss
        # eviction churn won this batch every round: serve it uncached
        # (one last pull, no pinning) instead of retrying unboundedly
        rows = np.asarray(pull_fn(np.asarray(uniq, dtype=np.int64)),
                          dtype=self.dtype).reshape(len(uniq), self.dim)
        pos = {i: j for j, i in enumerate(uniq)}
        with self._lock:
            self.hits += n_hit
            self.misses += n_miss
        return rows[[pos[i] for i in id_list]], n_hit, n_miss

    def _gathered(self, slots, n):
        padded = _pad_pow2(n)
        if padded != n:
            slots = np.concatenate(
                [slots, np.zeros(padded - n, dtype=np.int32)])
        return self._gather(self._buf, slots)[:n]

    def insert(self, ids, rows):
        """Pin rows (np [k, dim]) for ids, evicting LRU entries to fit."""
        ids = [int(i) for i in np.asarray(ids).ravel()]
        rows = np.asarray(rows, dtype=self.dtype).reshape(len(ids),
                                                          self.dim)
        self._ensure_buf()
        with self._lock:
            fresh = [(j, i) for j, i in enumerate(ids)
                     if i not in self._slot]
            # rows already resident just refresh their value in place
            upd_slots = [self._slot[i] for i in ids if i in self._slot]
            upd_rows = [rows[j] for j, i in enumerate(ids)
                        if i in self._slot]
            slots = self._take_slots(len(fresh))
            for (j, i), s in zip(fresh, slots):
                self._slot[i] = s
            all_slots = np.asarray(
                slots + upd_slots, dtype=np.int32)
            all_rows = np.concatenate(
                [rows[[j for j, _ in fresh]].reshape(len(fresh), self.dim),
                 np.asarray(upd_rows, dtype=self.dtype).reshape(
                     len(upd_rows), self.dim)], axis=0)
            n = len(all_slots)
            padded = _pad_pow2(n)
            if padded != n:
                # pad by re-writing the first slot with its own row: the
                # scatter stays shape-stable (O(log capacity) signatures)
                # and the duplicate write is a no-op
                all_slots = np.concatenate(
                    [all_slots,
                     np.full(padded - n, all_slots[0], dtype=np.int32)])
                all_rows = np.concatenate(
                    [all_rows,
                     np.broadcast_to(all_rows[0],
                                     (padded - n, self.dim))], axis=0)
            self._buf = self._scatter(self._buf, all_slots, all_rows)

    def refresh(self, ids, rows):
        """Overwrite the cached copies of whichever ``ids`` are resident
        (a training push's post-update rows); non-resident ids are left
        alone — a push must not PIN rows nobody looked up."""
        ids = np.asarray(ids).ravel()
        rows = np.asarray(rows, dtype=self.dtype).reshape(len(ids),
                                                          self.dim)
        with self._lock:
            at = [j for j, i in enumerate(ids.tolist())
                  if int(i) in self._slot]
        if at:
            self.insert(ids[at], rows[at])

    def invalidate(self, ids):
        """Drop rows (a training push made the cached copies stale)."""
        with self._lock:
            for i in np.asarray(ids).ravel().tolist():
                slot = self._slot.pop(int(i), None)
                if slot is not None:
                    self._free.append(slot)
