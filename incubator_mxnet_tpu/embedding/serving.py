"""Serving path: embedding fan-out in front of the dense tower.

A recommender request arrives as an id-set plus dense features.  The
path fans the ids out to the embedding shards (through the hot-row
cache, so hot ids never touch the network), assembles the dense input,
and submits it to the `ReplicaRouter` fleet serving the tower.

Failure composition (the chaos-certified matrix): a dense replica dying
is the router's problem — it already fails queued work over with zero
loss.  An embedding SHARD dying surfaces here as `ServerLostError`
during the fan-out; every admitted request retries through the
configured ``on_shard_lost`` recovery hook (respawn + `replace_shard`,
or a standby address) until the deadline, so a shard kill mid-traffic
loses zero admitted requests.  Requests whose ids are fully cache-hot
keep serving straight through a dead shard without ever noticing.
"""
from __future__ import annotations

import time

import numpy as np

from ..obs import metrics as _obs_metrics
from ..obs import trace as _trace
from ..resilience import ServerLostError


class EmbeddingServingPath:
    """Fan ids out to embedding shards, then the tower through a router."""

    def __init__(self, table, router, embed_input="emb",
                 on_shard_lost=None, retry_deadline_s=30.0):
        self.table = table
        self.router = router
        self.embed_input = str(embed_input)
        # recovery hook: called with the ServerLostError; returns True
        # when the shard has been re-attached (replace_shard) and the
        # fan-out should retry
        self.on_shard_lost = on_shard_lost
        self.retry_deadline_s = float(retry_deadline_s)
        self.requests = 0
        self.completed = 0
        self.shard_failovers = 0
        # join the scrape plane with the path-local counters only —
        # the table and router already register their own producers
        self._ns = f"embedding.serve.{self.table.name}"
        _obs_metrics.register_producer(self._ns, self._scrape)

    def _fan_out(self, ids):
        """Looked-up vectors for the request's id-set, surviving a shard
        death when a recovery hook is installed."""
        deadline = time.monotonic() + self.retry_deadline_s
        while True:
            try:
                return self.table.lookup(ids)
            except ServerLostError as e:
                if self.on_shard_lost is None:
                    raise
                self.shard_failovers += 1
                if not self.on_shard_lost(e) \
                        or time.monotonic() > deadline:
                    raise
                # recovered: the retry pulls from the re-attached shard

    def submit(self, ids, dense=None, timeout_ms=None,
               priority="interactive", request_id=None):
        """One request: ids (B,) or (B, slots) + optional extra dense
        inputs dict; returns the router's Future."""
        ids = np.asarray(ids, dtype=np.int64)
        self.requests += 1
        with _trace.span("embedding.serve", cat="embedding",
                         table=self.table.name, rows=int(ids.size)):
            vecs = self._fan_out(ids)
            flat = np.asarray(vecs).reshape(
                ids.shape[0], -1)
            inputs = {self.embed_input: flat}
            if dense:
                inputs.update(dense)
            fut = self.router.submit(inputs, timeout_ms=timeout_ms,
                                     priority=priority,
                                     request_id=request_id)
        self.completed += 1
        return fut

    def predict(self, ids, dense=None, timeout_ms=None):
        """Synchronous submit: the per-output array list."""
        fut = self.submit(ids, dense=dense, timeout_ms=timeout_ms)
        budget = (timeout_ms / 1e3) if timeout_ms else 30.0
        return fut.result(budget)

    def _scrape(self):
        return {"requests": self.requests, "completed": self.completed,
                "shard_failovers": self.shard_failovers}

    def stats(self):
        return dict(self._scrape(),
                    table=self.table.stats(),
                    router=self.router.stats())

    def close(self):
        _obs_metrics.unregister_producer(self._ns)
