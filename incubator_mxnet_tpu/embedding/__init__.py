"""mxembed: sharded sparse embeddings for recommender workloads.

The workload the source framework was famous for (PAPERS.md: the MXNet
paper's `row_sparse` + ps-lite push/pull design; the TensorFlow paper's
sparse embedding layers for production recommenders): embedding tables
too big for one device's HBM, range/hash-partitioned into row shards
hosted on the `dist_async` parameter servers, trained with lazy
row-sparse optimizer updates applied shard-side so only touched rows
ever move, and served through a device-resident hot-row LRU cache so the
steady-state lookup for hot ids never leaves HBM.

- `ShardedEmbedding`  — the sharded table client (push/pull, breakers,
  `ServerLostError` failover diagnosis, checkpoint capture/restore)
- `HotRowCache`       — device-resident LRU row cache (unified program
  cache, donation discipline, hit/miss/eviction stats)
- `EmbeddingFitAdapter` — trains a table through `Module.fit` by feeding
  looked-up vectors as a data input and pushing the input gradient back
  as row_sparse at each batch end
- `EmbeddingServingPath` — fans a request's id-set out to the embedding
  shards, then submits the dense tower through a `ReplicaRouter`
"""
from .cache import HotRowCache
from .sharded import ShardedEmbedding, shard_of_ids
from .fit import EmbeddingFitAdapter
from .serving import EmbeddingServingPath

__all__ = ["HotRowCache", "ShardedEmbedding", "shard_of_ids",
           "EmbeddingFitAdapter", "EmbeddingServingPath"]
