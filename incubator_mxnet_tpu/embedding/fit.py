"""Train a sharded embedding table through `Module.fit`.

The dense tower stays a plain Module program; the embedding rides along
as a DATA input: the adapter wraps the id-carrying iterator so each
batch's id field is replaced by its looked-up vectors (hot rows gather
from the device cache), and the module is bound with
``inputs_need_grad=True`` so the backward pass produces d(loss)/d(vectors)
— which IS the row-sparse embedding gradient.  A `batch_end_callback`
reads it from `get_input_grads`, folds the slot axis, pre-sums duplicate
ids, and pushes to the owning shards where the lazy optimizer applies
it.  `Module.fit`'s guardian, h2d ring, and checkpoint plane all ride
along untouched (binding with input grads selects the classic per-batch
step, which is what exposes the input gradient).
"""
from __future__ import annotations

import numpy as np

from ..io import DataBatch, DataDesc


class EmbeddingFitAdapter:
    """Wraps an id-carrying iterator + a `ShardedEmbedding` for fit.

    ``base_iter`` yields batches whose ``data[id_field]`` is an int
    array of row ids, shape (B,) or (B, slots); the adapter emits
    batches where that field is the looked-up vectors flattened to
    (B, slots*dim), remembers each batch's ids, and pushes the matching
    input gradient at batch end."""

    def __init__(self, table, base_iter, id_field=0, embed_name=None):
        self.table = table
        self._base = base_iter
        self._idx = int(id_field)
        self.batch_size = getattr(base_iter, "batch_size", 0)
        descs = list(base_iter.provide_data)
        d = descs[self._idx]
        shape = tuple(d.shape)
        self._slots = int(np.prod(shape[1:])) if len(shape) > 1 else 1
        self._name = embed_name or d.name
        descs[self._idx] = DataDesc(
            self._name, (shape[0], self._slots * table.dim))
        self.provide_data = descs
        self.provide_label = base_iter.provide_label
        self._last_ids = None
        self.pushes = 0

    # -- iterator protocol ----------------------------------------------------
    def reset(self):
        self._base.reset()

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def next(self):
        batch = self._base.next()
        data = list(batch.data)
        ids = np.asarray(data[self._idx].asnumpy()
                         if hasattr(data[self._idx], "asnumpy")
                         else data[self._idx]).astype(np.int64)
        vecs = self.table.lookup(ids)   # device array, no host hop
        from ..ndarray.ndarray import NDArray
        data[self._idx] = NDArray(vecs.reshape(
            ids.shape[0], self._slots * self.table.dim))
        self._last_ids = ids
        return DataBatch(data=data, label=batch.label, pad=batch.pad,
                         index=batch.index,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    # -- grad push ------------------------------------------------------------
    def push_from(self, module):
        """Push the embedding gradient of the LAST emitted batch (reads
        `get_input_grads` — the module must be bound with
        ``inputs_need_grad=True`` before fit)."""
        if self._last_ids is None:
            return
        grad = module.get_input_grads()[self._idx].asnumpy()
        ids = self._last_ids.ravel()
        self.table.push_grad(ids, grad.reshape(len(ids), self.table.dim))
        self.pushes += 1

    def make_callback(self, module):
        """The ``batch_end_callback`` for `Module.fit`."""
        def _cb(_param):
            self.push_from(module)
        return _cb
