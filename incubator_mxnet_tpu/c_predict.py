"""Python backend for the C predict ABI (`src/c_predict_api.cc`).

The reference ships a standalone inference ABI
(`include/mxnet/c_predict_api.h:78-200`: create a predictor from saved
symbol JSON + params bytes, set inputs, forward, read outputs) used by the
amalgamation/mobile builds.  The TPU build keeps the same surface: the C
shared library embeds CPython and drives THIS module, whose predictor
binds the symbol through the ordinary executor (one XLA program per
signature), so C callers get the same compiled inference path as Python
callers.
"""
from __future__ import annotations

import numpy as np

__all__ = ["Predictor", "create"]


class Predictor:
    def __init__(self, symbol_json, param_bytes, dev_type, dev_id,
                 input_shapes):
        from . import context as ctx_mod
        from . import symbol as sym_mod
        from .compat.mxnet_params import load_params
        from .executor import Executor

        ctx = (ctx_mod.cpu(dev_id) if dev_type == 1 else
               ctx_mod.tpu(dev_id))
        self._ctx = ctx
        sym = sym_mod.load_json(symbol_json)
        arg_names = sym.list_arguments()
        aux_names = sym.list_auxiliary_states()
        self._input_names = list(input_shapes)
        self._exec = Executor._simple_bind(sym, ctx, "null", None,
                                           dict(input_shapes))
        params = load_params(param_bytes)
        args, auxs = {}, {}
        for k, v in params.items():
            if ":" in k:
                kind, name = k.split(":", 1)
                (args if kind == "arg" else auxs)[name] = v
            elif k in arg_names:
                args[k] = v
            elif k in aux_names:
                auxs[k] = v
        self._exec.copy_params_from(args, auxs, allow_extra_params=True)
        self._outputs = None

    def output_count(self):
        return len(self._exec._symbol.list_outputs())

    def set_input(self, name, flat_f32):
        tgt = self._exec.arg_dict[name]
        arr = np.asarray(flat_f32, dtype=np.float32).reshape(tgt.shape)
        from .ndarray.ndarray import array
        if self._ctx.device_type != "cpu":
            # device_put is ASYNC and may read the caller's buffer after
            # this call returns; the ABI promises copy semantics, so take a
            # private host copy before handing it to the transfer
            arr = np.array(arr, copy=True)
        self._exec.arg_dict[name]._set_data(
            array(arr, ctx=self._ctx, dtype=tgt.dtype)._data)

    def set_input_bytes(self, name, view):
        """C ABI path: `view` is a read-only memoryview over float32."""
        self.set_input(name, np.frombuffer(view, dtype=np.float32))

    def forward(self):
        self._outputs = self._exec.forward(is_train=False)

    def output_shape(self, index):
        if self._outputs is None:
            self.forward()
        return tuple(self._outputs[index].shape)

    def output(self, index):
        """Flat float32 bytes of output `index`."""
        out = self._outputs[index].asnumpy().astype(np.float32, copy=False)
        return np.ascontiguousarray(out).tobytes()


def create(symbol_json, param_bytes, dev_type, dev_id, input_names,
           input_shapes):
    """ABI entry: input_names list[str], input_shapes list[tuple]."""
    return Predictor(symbol_json, param_bytes, dev_type, dev_id,
                     dict(zip(input_names, [tuple(s) for s in input_shapes])))
