"""Python backend for the C predict ABI (`src/c_predict_api.cc`).

The reference ships a standalone inference ABI
(`include/mxnet/c_predict_api.h:78-200`: create a predictor from saved
symbol JSON + params bytes, set inputs, forward, read outputs) used by the
amalgamation/mobile builds.  The TPU build keeps the same surface, but the
predictor is now a thin adapter over the serving runtime's single-request
path (`serving.ServedModel.infer`): the C parity API and a `ModelServer`
hosting the same model share ONE per-signature compiled-program cache
(`fused.FusedInference`), so a process that both serves traffic and
answers C-ABI calls compiles each shape exactly once.
"""
from __future__ import annotations

import numpy as np

__all__ = ["Predictor", "create"]


class Predictor:
    def __init__(self, symbol_json, param_bytes, dev_type, dev_id,
                 input_shapes, programs_dir=None):
        from . import context as ctx_mod
        from . import symbol as sym_mod
        from .compat.mxnet_params import load_params
        from .serving.model import ServedModel

        if programs_dir:
            # pre-compiled program payload (compile/ subsystem): the
            # first forward loads its executable from disk instead of
            # paying the XLA compile — the mobile/embedded cold-start fix
            from . import compile as _compile
            _compile.add_source(programs_dir)
        ctx = (ctx_mod.cpu(dev_id) if dev_type == 1 else
               ctx_mod.tpu(dev_id))
        self._ctx = ctx
        sym = sym_mod.load_json(symbol_json)
        arg_names = sym.list_arguments()
        aux_names = sym.list_auxiliary_states()
        input_shapes = {k: tuple(v) for k, v in dict(input_shapes).items()}
        self._input_shapes = input_shapes
        self._input_names = list(input_shapes)
        params = load_params(param_bytes)
        if not isinstance(params, dict):   # nameless save of zero params
            params = {}
        args, auxs = {}, {}
        for k, v in params.items():
            if ":" in k:
                kind, name = k.split(":", 1)
                (args if kind == "arg" else auxs)[name] = v
            elif k in arg_names:
                args[k] = v
            elif k in aux_names:
                auxs[k] = v
        # the ABI declares ONE exact signature: a single bucket sized to
        # the declared batch, compiled on first forward (no warmup pass —
        # the first call pays the one compile either way).  Each predictor
        # audits under its own key so two predictors in one process don't
        # read as each other's shape churn.
        batch = max(int(next(iter(input_shapes.values()))[0]), 1) \
            if input_shapes else 1
        Predictor._seq = getattr(Predictor, "_seq", 0) + 1
        self._model = ServedModel(sym, args, auxs,
                                  data_shapes=list(input_shapes.items()),
                                  buckets=(batch,), ctx=ctx,
                                  name=f"c_predict#{Predictor._seq}")
        self._inputs = {name: np.zeros(shape, np.float32)
                        for name, shape in input_shapes.items()}
        self._outputs = None

    def output_count(self):
        return len(self._model.output_names)

    def set_input(self, name, flat_f32):
        shape = self._input_shapes[name]
        # the ABI promises copy semantics: the caller's buffer may be
        # reused the moment this returns, so take a private host copy
        self._inputs[name] = np.array(flat_f32, dtype=np.float32,
                                      copy=True).reshape(shape)
        self._outputs = None

    def set_input_bytes(self, name, view):
        """C ABI path: `view` is a read-only memoryview over float32."""
        self.set_input(name, np.frombuffer(view, dtype=np.float32))

    def forward(self):
        # exact declared shapes, no batch-axis coalescing semantics: the
        # ABI's inputs need not share a leading dimension (a (8, 784)
        # data input next to a (1, 256) state input is legal)
        self._outputs = self._model.infer_exact(self._inputs)

    def output_shape(self, index):
        if self._outputs is None:
            self.forward()
        return tuple(self._outputs[index].shape)

    def output(self, index):
        """Flat float32 bytes of output `index`."""
        out = self._outputs[index].asnumpy().astype(np.float32, copy=False)
        return np.ascontiguousarray(out).tobytes()


def create(symbol_json, param_bytes, dev_type, dev_id, input_names,
           input_shapes, programs_dir=None):
    """ABI entry: input_names list[str], input_shapes list[tuple];
    `programs_dir` optionally names a pre-compiled program payload."""
    return Predictor(symbol_json, param_bytes, dev_type, dev_id,
                     dict(zip(input_names, [tuple(s) for s in input_shapes])),
                     programs_dir=programs_dir)
