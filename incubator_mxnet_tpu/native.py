"""ctypes loader for the native IO library (`src/io_native.cc`).

The reference ships its data plane in C++ (`src/io/`); here the hot
kernels live in `libmxtpu_io.so`, built lazily with the in-image
toolchain on first use and cached beside the sources.  Everything using
this module must keep a numpy fallback: `lib()` returns None when no
compiler is available or `MXNET_USE_NATIVE_IO=0`.
"""
from __future__ import annotations

import ctypes
import os
import subprocess

from .analysis import locks as _alocks

_SRC_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "src")
_LIB_PATH = os.path.join(_SRC_DIR, "libmxtpu_io.so")

_lock = _alocks.make_lock("native")
_lib = None
_tried = False


def _configure(lib):
    i64 = ctypes.c_int64
    lib.mxtpu_recordio_index.restype = i64
    lib.mxtpu_recordio_index.argtypes = [
        ctypes.c_void_p, i64, ctypes.POINTER(i64), ctypes.POINTER(i64),
        ctypes.POINTER(ctypes.c_int32), i64]
    lib.mxtpu_augment_to_chw.restype = None
    lib.mxtpu_augment_to_chw.argtypes = [
        ctypes.c_void_p, i64, i64, i64, i64, i64, i64, i64, ctypes.c_int,
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_float), ctypes.c_int]
    lib.mxtpu_augment_batch.restype = None
    lib.mxtpu_augment_batch.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(i64),
        ctypes.POINTER(i64), i64, ctypes.POINTER(i64), ctypes.POINTER(i64),
        i64, i64, ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_float), i64, ctypes.c_int]
    if hasattr(lib, "mxtpu_crop_batch_u8"):
        # absent in prebuilt libraries older than device-augment mode;
        # image.py guards with hasattr and falls back to numpy for THIS
        # kernel only — the rest of the library must stay usable
        lib.mxtpu_crop_batch_u8.restype = None
        lib.mxtpu_crop_batch_u8.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(i64),
            ctypes.POINTER(i64), i64, ctypes.POINTER(i64),
            ctypes.POINTER(i64), i64, i64, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_uint8), i64, ctypes.c_int]
    return lib


def lib():
    """The loaded native library, building it if needed; None if
    unavailable (callers fall back to numpy)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("MXNET_USE_NATIVE_IO", "1") == "0":
            return None
        try:
            src = os.path.join(_SRC_DIR, "io_native.cc")
            have_lib = os.path.exists(_LIB_PATH)
            # rebuild when the source is newer; a prebuilt .so without
            # sources (deployed image) is used as-is
            stale = (os.path.exists(src)
                     and (not have_lib
                          or os.path.getmtime(_LIB_PATH)
                          < os.path.getmtime(src)))
            if stale:
                subprocess.run(["make", "-C", _SRC_DIR, "-s"], check=True,
                               capture_output=True, timeout=120)
            elif not have_lib:
                return None
            _lib = _configure(ctypes.CDLL(_LIB_PATH))
        except Exception:
            _lib = None
        return _lib
