"""Network visualization (reference `python/mxnet/visualization.py`):
print_summary + plot_network (graphviz-gated)."""
from __future__ import annotations

import json

from .base import MXNetError


def print_summary(symbol, shape=None, line_length=120, positions=(.44, .64, .74, 1.)):
    """Reference `visualization.py print_summary`."""
    if shape is not None:
        interals = symbol.get_internals()
        _, out_shapes, _ = interals.infer_shape_partial(**shape)
        if out_shapes is None:
            raise ValueError("Input shape is incomplete")
        shape_dict = dict(zip(interals.list_outputs(), out_shapes))
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]

    def print_row(fields, positions_):
        line = ""
        for i, field in enumerate(fields):
            line += str(field)
            line = line[:positions_[i]]
            line += " " * (positions_[i] - len(line))
        print(line)

    positions = [int(line_length * p) for p in positions]
    print("_" * line_length)
    print_row(["Layer (type)", "Output Shape", "Param #", "Previous Layer"],
              positions)
    print("=" * line_length)
    total_params = 0

    def count_params(node):
        nonlocal total_params
        op = node["op"]
        if op == "null":
            return 0
        return 0

    for i, node in enumerate(nodes):
        op = node["op"]
        name = node["name"]
        if op == "null":
            continue
        out_shape = ""
        key = name + "_output"
        if shape is not None and key in shape_dict:
            out_shape = str(shape_dict[key])
        pre_nodes = [nodes[item[0]]["name"] for item in node["inputs"]
                     if nodes[item[0]]["op"] != "null"]
        # parameter count: sum of variable-input sizes
        params = 0
        if shape is not None:
            for item in node["inputs"]:
                src = nodes[item[0]]
                if src["op"] == "null" and not (
                        src["name"].endswith("data") or
                        src["name"].endswith("label")):
                    skey = src["name"] + "_output"
                    # variables appear in internals as their own outputs
        print_row([f"{name}({op})", out_shape, params,
                   ",".join(pre_nodes)], positions)
    print("=" * line_length)


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Reference `visualization.py plot_network` — requires graphviz."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("Draw network requires graphviz library") from None
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    dot = Digraph(name=title)
    hidden = set()
    for i, node in enumerate(nodes):
        op = node["op"]
        name = node["name"]
        if op == "null":
            if hide_weights and not name.endswith("data"):
                hidden.add(i)
                continue
            dot.node(name=name, label=name, shape="oval")
        else:
            dot.node(name=name, label=f"{name}\n{op}", shape="box")
    for i, node in enumerate(nodes):
        if node["op"] == "null" or i in hidden:
            continue
        for item in node["inputs"]:
            if item[0] in hidden:
                continue
            dot.edge(nodes[item[0]]["name"], node["name"])
    return dot
