"""Cross-host serving fleet: placement, SLO autoscaling, host-loss survival.

The router (router.py) made the REPLICA the unit of redundancy; this
module makes the HOST one.  Before it, every `RemoteReplica` lived on
localhost — one dead machine took the whole fleet down, and a traffic
ramp had no way to recruit capacity.  `FleetManager` composes the
repo's existing ingredients into the fleet layer both reference papers
describe (the TensorFlow paper's production serving story; the MLPerf
pods paper's host-level liveness, already reproduced for *training* in
`dist/membership.py`):

* **host-aware placement** — replicas are spawned across a registry of
  `FleetHost` handles with anti-affinity: each new replica lands on the
  live host carrying the fewest of this model's replicas, so one host
  death costs 1/H of capacity, never all of it.  A host whose spawns
  keep failing trips its per-host `CircuitBreaker` and placement skips
  it while it cools off.

* **host liveness via `dist.membership`** — the fleet heartbeats every
  host agent on an interval and feeds the SAME `MembershipTable` the
  elastic trainer uses; a host whose beats go silent past the deadline
  is dead in the next view.  A dead host marks ALL its replicas dead at
  once (`router.declare_lost`), so in-flight requests fail over
  immediately instead of waiting out each replica's own probe silence,
  and the fleet re-places the lost capacity on survivors (backfill —
  its latency is a stat, not a hope).

* **SLO-driven autoscaling** — the `Autoscaler` watches the SAME
  queue-model signal the admission controller sheds on
  (`router.estimated_wait_s()`): sustained est-wait above the SLO
  spawns a replica (warm spinup — with a shared program-cache dir the
  worker certifies ZERO XLA compiles in its READY line, and a compiling
  spinup is a WARN finding); sustained idle retires one through the
  router's drain path.  Hysteresis (a dead band between the breach and
  idle thresholds), a cooldown after every action, and a min/max
  replica budget make the loop flap-proof: an oscillating signal resets
  the streaks and can never thrash the fleet.

* **graceful degradation** — capacity loss raises est-wait, the
  router's admission controller sheds best_effort FIRST (unchanged
  policy, same signal), interactive p99 rides inside its SLO band while
  the autoscaler backfills; `tools/run_chaos.py --fleet` certifies the
  whole story against a real SIGKILLed host.

Fault sites (`resilience.faults`): ``fleet.spawn`` (per replica spawn,
names host + replica) and ``host.down`` (per host probe — a ``drop``
clause simulates host silence without killing anything).
"""
from __future__ import annotations

import collections
import itertools
import threading
import time

from ..analysis import locks as _locks
from ..analysis import tsan as _tsan
from ..base import MXNetError
from ..dist.membership import MembershipTable
from ..obs import metrics as _obs_metrics
from ..resilience import CircuitBreaker, faults as _faults

__all__ = ["FleetManager", "Autoscaler", "ReplicaSpec", "FleetHost",
           "InProcessHost", "AgentHost"]

# module-level fleet event log for analysis.runtime_report(): every
# scale/host event from every live FleetManager, bounded
_EVENTS = collections.deque(maxlen=512)
_EVENTS_LOCK = _locks.make_lock("serving.fleet.events")


def _note_event(fleet, action, **ctx):
    entry = {"fleet": fleet, "action": action, **ctx}
    with _EVENTS_LOCK:
        _EVENTS.append(entry)
    from .. import profiler as _profiler
    _profiler.record_serving(f"fleet:{fleet}", 0.0, event=action,
                             **{k: v for k, v in ctx.items()
                                if isinstance(v, (str, int, float, bool))})
    return entry


def findings():
    """Fleet findings for `analysis.runtime_report()`: host losses and
    backfills as WARNs (capacity events someone should know about), a
    WARN for any scale-up that compiled XLA programs (the warm-spinup
    contract is ZERO — warm the shared program cache), and one HINT
    summarizing scale traffic per fleet."""
    from ..analysis.findings import Finding, HINT, WARN
    with _EVENTS_LOCK:
        events = list(_EVENTS)
    out = []
    per_fleet = collections.Counter()
    for e in events:
        per_fleet[e["fleet"]] += 1
        if e["action"] == "host_down":
            out.append(Finding(
                "serving.fleet", "host-lost", WARN,
                "fleet '%s': host '%s' declared dead (%s) — %d replica(s) "
                "failed over and re-placed on survivors"
                % (e["fleet"], e.get("host"), e.get("reason", "?"),
                   e.get("replicas", 0)),
                location="serving.fleet"))
        elif e["action"] == "backfill_complete":
            out.append(Finding(
                "serving.fleet", "backfill", WARN,
                "fleet '%s': backfilled to target %d in %.2fs after "
                "capacity loss"
                % (e["fleet"], e.get("target", 0),
                   e.get("latency_s", 0.0)),
                location="serving.fleet"))
        elif e["action"] == "scale_up" and e.get("spinup_compiles"):
            out.append(Finding(
                "serving.fleet", "cold-spinup", WARN,
                "fleet '%s': scale-up of '%s' on host '%s' compiled %d "
                "XLA program(s) — warm spinup should be ZERO-compile; "
                "share MXNET_PROGRAM_CACHE_DIR across the fleet"
                % (e["fleet"], e.get("replica"), e.get("host"),
                   e.get("spinup_compiles")),
                location="serving.fleet"))
    for fleet, n in sorted(per_fleet.items()):
        ups = sum(1 for e in events
                  if e["fleet"] == fleet and e["action"] == "scale_up")
        downs = sum(1 for e in events
                    if e["fleet"] == fleet and e["action"] == "scale_down")
        out.append(Finding(
            "serving.fleet", "summary", HINT,
            "fleet '%s': %d event(s) — %d scale-up, %d scale-down"
            % (fleet, n, ups, downs), location="serving.fleet"))
    return out


def reset_findings():
    with _EVENTS_LOCK:
        _EVENTS.clear()


class ReplicaSpec:
    """What to spawn: one served model's worker recipe, JSON-able so a
    host agent on another machine can execute it (`to_msg`/`from_msg`
    round-trip over the transport frames)."""

    __slots__ = ("name", "prefix", "epoch", "symbol_file",
                 "checkpoint_dir", "data_shapes", "buckets", "env",
                 "concurrency")

    def __init__(self, *, data_shapes, name="model", prefix=None, epoch=0,
                 symbol_file=None, checkpoint_dir=None,
                 buckets=(1, 2, 4, 8), env=None, concurrency=2):
        self.name = str(name)
        self.prefix = prefix
        self.epoch = int(epoch)
        self.symbol_file = symbol_file
        self.checkpoint_dir = checkpoint_dir
        self.data_shapes = [(str(n), tuple(int(d) for d in s))
                            for n, s in data_shapes]
        self.buckets = tuple(int(b) for b in buckets)
        self.env = dict(env or {})
        self.concurrency = int(concurrency)

    def to_msg(self):
        return {"name": self.name, "prefix": self.prefix,
                "epoch": self.epoch, "symbol_file": self.symbol_file,
                "checkpoint_dir": self.checkpoint_dir,
                "data_shapes": [[n, list(s)] for n, s in self.data_shapes],
                "buckets": list(self.buckets), "env": dict(self.env),
                "concurrency": self.concurrency}

    @classmethod
    def from_msg(cls, msg):
        return cls(data_shapes=[(n, tuple(s))
                                for n, s in msg["data_shapes"]],
                   name=msg.get("name", "model"),
                   prefix=msg.get("prefix"),
                   epoch=msg.get("epoch", 0),
                   symbol_file=msg.get("symbol_file"),
                   checkpoint_dir=msg.get("checkpoint_dir"),
                   buckets=msg.get("buckets", (1, 2, 4, 8)),
                   env=msg.get("env"),
                   concurrency=msg.get("concurrency", 2))


class FleetHost:
    """One serving host the fleet can place replicas on.

    The contract: ``heartbeat()`` raises when the host is unreachable
    (the membership deadline turns sustained failure into death);
    ``spawn_replica(spec, replica_id)`` starts one worker THERE and
    returns the router-side `Replica` handle."""

    host_id = "?"

    def heartbeat(self):
        raise NotImplementedError

    def spawn_replica(self, spec, replica_id):
        raise NotImplementedError

    def scrape(self):
        """The host's telemetry snapshot ({"values", "prom"}), or None
        when this host kind has no scrape leg (in-process hosts share
        the manager's own registry)."""
        return None

    def close(self):
        pass


class InProcessHost(FleetHost):
    """A logical host inside this process: ``spawn`` is a caller-supplied
    factory (tests and the bench hand it a `LocalReplica` builder), and
    liveness is a flag tests flip.  The autoscaler/placement logic is
    identical to the cross-host path — only the actuation is local."""

    def __init__(self, host_id, spawn=None):
        self.host_id = str(host_id)
        self._spawn = spawn
        self._down = False

    def heartbeat(self):
        if self._down:
            raise MXNetError(f"host '{self.host_id}' is down")
        return {"ok": True, "host_id": self.host_id}

    def spawn_replica(self, spec, replica_id):
        if self._down:
            raise MXNetError(f"host '{self.host_id}' is down")
        if self._spawn is None:
            raise MXNetError(
                f"host '{self.host_id}': no spawn factory configured")
        return self._spawn(spec, replica_id)

    def fail(self):
        """Simulate host death (tests): heartbeats fail from now on."""
        self._down = True

    def recover(self):
        self._down = False


class AgentHost(FleetHost):
    """A host fronted by its `serving.hostd` agent daemon.

    Two serial channels: a short-timeout control channel (heartbeats
    answer in microseconds or the host is in trouble) and a separate
    long-timeout spawn channel (a cold worker warmup legitimately takes
    a while; it must not block the next heartbeat)."""

    def __init__(self, host_id, host, port, process=None,
                 control_timeout=5.0, spawn_timeout=300.0):
        self.host_id = str(host_id)
        self.host, self.port = str(host), int(port)
        self.process = process       # Popen when launch_local()ed
        self._control = self._make_channel(control_timeout)
        self._spawn_chan = self._make_channel(spawn_timeout)

    def _make_channel(self, timeout):
        from ..dist.transport import Channel
        from ..resilience import RetryPolicy
        # short connect window: a dead host should be DIAGNOSED in ~a
        # couple of seconds so the membership deadline can act, not
        # nursed through a long reconnect budget
        return Channel(self.host, self.port, timeout=timeout,
                       connect_wait=2.0,
                       retry=RetryPolicy(max_attempts=2, base_delay=0.05,
                                         max_delay=0.2))

    @classmethod
    def connect(cls, host_id, endpoint, **kw):
        """Attach to an ALREADY-RUNNING host daemon by endpoint —
        ``"host:port"`` / ``":port"`` / ``"port"``
        (`dist.transport.parse_endpoint` spellings).  The production
        cross-host path: an operator starts ``python -m
        incubator_mxnet_tpu.serving.hostd`` on each machine and hands
        the fleet the endpoints; `launch_local` is the single-machine
        convenience around the same protocol."""
        from ..dist.transport import parse_endpoint
        host, port = parse_endpoint(endpoint)
        return cls(host_id, host, port, **kw)

    @classmethod
    def launch_local(cls, host_id, bind_host="127.0.0.1", env=None,
                     ready_timeout=60.0, launch=None):
        """Start a host daemon — locally by default, or anywhere via the
        ``launch(cmd, env) -> Popen`` hook (ssh wrapper, container exec).
        The daemon and every worker it spawns share one process group
        (``start_new_session``), so a SIGKILL of the group is a faithful
        whole-host power-off (the chaos schedule's weapon).  The
        launch-and-handshake loop is `replica.launch_worker` — one
        implementation for workers AND daemons."""
        import sys
        from .replica import launch_worker
        cmd = [sys.executable, "-m", "incubator_mxnet_tpu.serving.hostd",
               "--host-id", str(host_id), "--host", bind_host]
        proc, port, _ready = launch_worker(
            cmd, env=env, name=f"hostd '{host_id}'",
            ready_timeout=ready_timeout, launch=launch, tag=host_id,
            port_prefix="HOSTD_PORT", ready_prefix="HOSTD_READY",
            start_new_session=True, thread_prefix="mx-hostd")
        return cls(host_id, bind_host, port, process=proc)

    def _request(self, chan, msg):
        reply = chan.request(msg)
        if "error" in reply:
            raise MXNetError(reply["error"])
        return reply

    def heartbeat(self):
        return self._request(self._control, {"cmd": "hb"})

    def scrape(self):
        """The daemon process's registry snapshot over the control
        channel (the fleet-wide scrape's per-host leg)."""
        reply = self._request(self._control, {"cmd": "metrics"})
        return {"values": dict(reply.get("values") or {}),
                "prom": reply.get("prom", "")}

    def spawn_replica(self, spec, replica_id):
        from .replica import RemoteReplica
        reply = self._request(self._spawn_chan,
                              {"cmd": "spawn", "spec": spec.to_msg(),
                               "replica_id": replica_id})
        rep = RemoteReplica(self.host, int(reply["port"]),
                            replica_id=replica_id,
                            concurrency=spec.concurrency)
        rep.ready_info = dict(reply.get("ready", {}))
        return rep

    def close(self):
        try:
            self._control.bare_request({"cmd": "stop"})
        except Exception:
            pass
        for chan in (self._control, self._spawn_chan):
            try:
                chan.close()
            except Exception:
                pass
        if self.process is not None:
            try:
                self.process.wait(timeout=10)
            except Exception:
                self.process.kill()

    def kill(self):
        """SIGKILL the whole host process group (chaos): the daemon AND
        every worker it spawned die with no flush, no unwinding."""
        import os
        import signal
        if self.process is not None:
            try:
                os.killpg(self.process.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                self.process.kill()


class Autoscaler:
    """The scale decision, isolated from actuation so seeded est-wait
    traces drive it deterministically in tests (injectable clock, no
    threads, no subprocesses).

    ``observe(est_wait_ms, live, busy)`` returns ``(action, reason)``
    where action is "up", "down", or None:

    * est-wait above ``slo_ms`` (or None — no live capacity at all)
      starts/extends the BREACH streak; sustained past ``up_after_s``
      and outside the cooldown -> "up" (clamped at ``max_replicas``).
    * est-wait below ``idle_fraction * slo_ms`` with nothing in flight
      starts/extends the IDLE streak; sustained past ``down_after_s``
      and outside the cooldown -> "down" (clamped at ``min_replicas``).
    * anything between the two thresholds is the HYSTERESIS dead band:
      both streaks reset, so a signal oscillating around the SLO can
      never accumulate a decision — and every action arms the cooldown,
      so even a pathological square-wave signal is rate-limited to one
      scale event per ``cooldown_s``.
    """

    def __init__(self, slo_ms, *, up_after_s, down_after_s, cooldown_s,
                 min_replicas, max_replicas, idle_fraction=0.1,
                 clock=time.monotonic):
        if int(min_replicas) < 0 or int(max_replicas) < int(min_replicas):
            raise MXNetError(
                f"autoscaler: invalid replica budget "
                f"[{min_replicas}, {max_replicas}]")
        self.slo_ms = float(slo_ms)
        self.up_after_s = float(up_after_s)
        self.down_after_s = float(down_after_s)
        self.cooldown_s = float(cooldown_s)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.idle_fraction = float(idle_fraction)
        self._clock = clock
        self._breach_since = None
        self._idle_since = None
        self._cooldown_until = 0.0
        self.clamped_at_max = 0
        self.clamped_at_min = 0

    def cooldown_remaining_s(self):
        return max(self._cooldown_until - self._clock(), 0.0)

    def streaks(self):
        now = self._clock()
        return {
            "breach_s": (now - self._breach_since
                         if self._breach_since is not None else 0.0),
            "idle_s": (now - self._idle_since
                       if self._idle_since is not None else 0.0)}

    def observe(self, est_wait_ms, live, busy):
        now = self._clock()
        breach = est_wait_ms is None or est_wait_ms > self.slo_ms
        idle = (not breach and not busy
                and est_wait_ms <= self.idle_fraction * self.slo_ms)
        if breach:
            self._idle_since = None
            if self._breach_since is None:
                self._breach_since = now
            sustained = now - self._breach_since
            if sustained >= self.up_after_s and now >= self._cooldown_until:
                if live >= self.max_replicas:
                    # count EPISODES (one per sustain window), not
                    # ticks: resetting the streak means a continuous
                    # clamped breach increments once per up_after_s,
                    # independent of the caller's tick rate
                    self.clamped_at_max += 1
                    self._breach_since = None
                    return None, None
                self._breach_since = None
                self._cooldown_until = now + self.cooldown_s
                wait = ("no live capacity" if est_wait_ms is None
                        else f"est-wait {est_wait_ms:.0f} ms > SLO "
                             f"{self.slo_ms:g} ms")
                return "up", f"{wait} sustained {sustained:.1f}s"
        elif idle:
            self._breach_since = None
            if self._idle_since is None:
                self._idle_since = now
            sustained = now - self._idle_since
            if sustained >= self.down_after_s \
                    and now >= self._cooldown_until:
                if live <= self.min_replicas:
                    self.clamped_at_min += 1
                    self._idle_since = None    # episode, not tick, count
                    return None, None
                self._idle_since = None
                self._cooldown_until = now + self.cooldown_s
                return "down", (
                    f"est-wait {est_wait_ms:.1f} ms < "
                    f"{self.idle_fraction * self.slo_ms:g} ms idle "
                    f"threshold sustained {sustained:.1f}s")
        else:
            # the dead band: neither overloaded nor provably idle
            self._breach_since = None
            self._idle_since = None
        return None, None


class _HostState:
    """Fleet-side bookkeeping for one host."""

    def __init__(self, rank, handle, breaker):
        self.rank = rank             # membership-table rank
        self.handle = handle
        self.breaker = breaker       # trips on consecutive spawn failures
        self.alive = True
        self.beats = 0
        self.hb_failures = 0         # consecutive


class FleetManager:
    """The fleet control loop over a `ReplicaRouter` (module docstring).

    ``hosts`` is the host registry (`FleetHost` handles); ``spec`` is
    the one model this fleet scales (multi-model fleets run one manager
    per model — placement is per-model anti-affinity by definition).
    The manager owns placement, host liveness, and the autoscaler; the
    router keeps owning dispatch, replica health, failover, and
    admission shedding — both act on the same est-wait signal.
    """

    def __init__(self, hosts, spec, router=None, name="fleet",
                 target_replicas=None, min_replicas=None,
                 max_replicas=None, slo_ms=None, tick_s=None,
                 up_after_s=None, down_after_s=None, cooldown_s=None,
                 idle_fraction=None, host_heartbeat_s=None,
                 host_deadline_s=None, clock=time.monotonic, start=True):
        from .. import config as _config
        from .router import ReplicaRouter
        if not hosts:
            raise MXNetError("fleet: at least one host is required")
        ids = [h.host_id for h in hosts]
        if len(set(ids)) != len(ids):
            raise MXNetError(f"fleet: duplicate host ids in {ids}")
        self.name = str(name)
        self.spec = spec
        self._clock = clock
        self.router = router if router is not None \
            else ReplicaRouter(name=f"{self.name}-router")
        self._owns_router = router is None

        def knob(value, key):
            return value if value is not None else _config.get(key)

        self.tick_s = float(knob(tick_s, "MXNET_FLEET_TICK_S"))
        self.host_heartbeat_s = float(
            knob(host_heartbeat_s, "MXNET_FLEET_HOST_HEARTBEAT_S"))
        self.host_deadline_s = float(
            knob(host_deadline_s, "MXNET_FLEET_HOST_DEADLINE_S"))
        min_r = int(knob(min_replicas, "MXNET_FLEET_MIN_REPLICAS"))
        max_r = int(knob(max_replicas, "MXNET_FLEET_MAX_REPLICAS"))
        self.autoscaler = Autoscaler(
            float(knob(slo_ms, "MXNET_FLEET_SLO_MS")),
            up_after_s=float(knob(up_after_s, "MXNET_FLEET_UP_AFTER_S")),
            down_after_s=float(
                knob(down_after_s, "MXNET_FLEET_DOWN_AFTER_S")),
            cooldown_s=float(knob(cooldown_s, "MXNET_FLEET_COOLDOWN_S")),
            min_replicas=min_r, max_replicas=max_r,
            idle_fraction=float(
                knob(idle_fraction, "MXNET_FLEET_IDLE_FRACTION")),
            clock=clock)
        self.target = int(target_replicas if target_replicas is not None
                          else max(min_r, 1))
        if not min_r <= self.target <= max_r:
            raise MXNetError(
                f"fleet '{self.name}': target {self.target} outside the "
                f"replica budget [{min_r}, {max_r}]")
        self._lock = _locks.make_lock("serving.fleet")
        _tsan.instrument(self, f"serving.fleet[{self.name}]")
        _obs_metrics.register_producer(
            "fleet" if self.name == "fleet" else f"fleet.{self.name}",
            self.stats)
        self._placement = {}          # replica_id -> host_id
        self._rid_seq = itertools.count(1)
        # host liveness rides the SAME MembershipTable the elastic
        # trainer uses: rank = registry index, deadline = host death
        self.membership = MembershipTable(len(hosts),
                                          self.host_deadline_s,
                                          clock=clock)
        self._hosts = {}
        for rank, handle in enumerate(hosts):
            breaker = CircuitBreaker(
                failure_threshold=int(
                    _config.get("MXNET_SERVING_BREAKER_THRESHOLD")),
                reset_timeout=float(
                    _config.get("MXNET_SERVING_BREAKER_RESET_S")))
            self._hosts[handle.host_id] = _HostState(rank, handle, breaker)
            # optimistic initial beat: a host that NEVER answers must
            # still age into the dead list (the table only judges hosts
            # it has seen)
            self.membership.heartbeat(rank, self.membership.epoch,
                                      label=handle.host_id)
        # counters / events
        self.scale_ups = 0
        self.scale_downs = 0
        self.hosts_lost = 0
        self.backfills = 0
        self.spawn_failures = 0
        self.last_backfill_s = None
        self._backfill_started = None   # capacity-loss timestamp
        self._scale_reason = None       # last autoscale decision's why
        self._events = collections.deque(maxlen=256)
        self._last_signal_ms = None
        self._closed = threading.Event()
        self._thread = None
        self._placer = None
        self._probers = []
        if start:
            self.start()

    # -- lifecycle ------------------------------------------------------------
    def start(self):
        """Place the initial fleet and start the control loops: ONE
        prober thread per host (a dead host's blocking connect attempts
        must never starve another host's membership beats — probing
        serially is how a single dead machine gets every healthy host
        falsely declared dead), the WATCH loop (liveness + autoscale
        decisions, never blocks on actuation), and the PLACER loop
        (spawns/retires toward target — a cold spawn can take minutes,
        and a second host dying during it must still be declared dead
        by the watch loop immediately, not after the spawn returns)."""
        if self._thread is not None:
            return self
        # probers BEFORE placement: the initial spawns can take seconds
        # (a cold ladder compile), and the constructor's seed beats must
        # not age past the deadline while they run
        self._probers = []
        for hs in self._hosts.values():
            t = threading.Thread(
                target=self._probe_loop, args=(hs,), daemon=True,
                name=f"mx-fleet-{self.name}-hb-{hs.handle.host_id}")
            t.start()
            self._probers.append(t)
        self._reconcile("initial placement")
        self._thread = threading.Thread(
            target=self._watch_loop, daemon=True,
            name=f"mx-fleet-{self.name}")
        self._thread.start()
        self._placer = threading.Thread(
            target=self._place_loop, daemon=True,
            name=f"mx-fleet-{self.name}-placer")
        self._placer.start()
        return self

    def shutdown(self, drain=True, close_hosts=False):
        self._closed.set()
        if self._thread is not None:
            _tsan.join_thread(self._thread, 30,
                              owner=f"FleetManager[{self.name}]")
            _tsan.join_thread(self._placer, 30,
                              owner=f"FleetManager[{self.name}]")
            for t in self._probers:
                _tsan.join_thread(t, 15,
                                  owner=f"FleetManager[{self.name}]")
        if self._owns_router:
            self.router.shutdown(drain=drain)
        if close_hosts:
            for hs in list(self._hosts.values()):
                try:
                    hs.handle.close()
                except Exception:
                    pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=True)

    # -- placement ------------------------------------------------------------
    def _live_hosts(self):
        with self._lock:
            return [hs for hs in self._hosts.values() if hs.alive]

    def _placed_on(self, host_id):
        with self._lock:
            return [rid for rid, hid in self._placement.items()
                    if hid == host_id]

    def _pick_host(self):
        """Anti-affinity: the live host (breaker permitting) carrying
        the fewest of this fleet's replicas; registry order breaks
        ties.  None when no host can take work."""
        with self._lock:
            crowd = collections.Counter(self._placement.values())
            cands = [hs for hs in self._hosts.values()
                     if hs.alive and hs.breaker.state != "open"]
        cands.sort(key=lambda hs: (crowd[hs.handle.host_id], hs.rank))
        for hs in cands:
            if hs.breaker.allow():
                return hs
        return None

    def _spawn_one(self, reason):
        hs = self._pick_host()
        if hs is None:
            states = {h.handle.host_id: ("alive" if h.alive else "dead",
                                         h.breaker.state)
                      for h in self._hosts.values()}
            raise MXNetError(
                f"fleet '{self.name}': no live host can take a replica "
                f"(hosts: {states})")
        host_id = hs.handle.host_id
        rid = f"{self.spec.name}@{host_id}/{next(self._rid_seq)}"
        t0 = self._clock()
        try:
            _faults.fire("fleet.spawn", host=host_id, replica=rid)
            replica = hs.handle.spawn_replica(self.spec, rid)
        except Exception as exc:
            hs.breaker.record_failure()
            with self._lock:
                self.spawn_failures += 1
            self._event("spawn_failed", host=host_id, replica=rid,
                        reason=f"{type(exc).__name__}: {exc}")
            raise MXNetError(
                f"fleet '{self.name}': spawning {rid} on host "
                f"'{host_id}' failed: {exc}") from exc
        hs.breaker.record_success()
        self.router.add_replica(replica)
        ready = dict(getattr(replica, "ready_info", None) or {})
        with self._lock:
            self._placement[rid] = host_id
        self._event("scale_up", host=host_id, replica=rid, reason=reason,
                    duration_s=round(self._clock() - t0, 3),
                    spinup_compiles=ready.get("compiles"),
                    spinup_disk_hits=ready.get("disk_hits"))
        with self._lock:
            self.scale_ups += 1
        return rid

    def _retire_one(self, reason):
        """Scale-down through the router's drain path: pick a replica on
        the MOST crowded host (re-balancing toward anti-affinity), the
        one with the least outstanding work."""
        with self._lock:
            placement = dict(self._placement)
        if not placement:
            return None
        crowd = collections.Counter(placement.values())
        slots = self._router_slots()

        def key(rid):
            slot = slots.get(rid)
            out = slot.replica.outstanding() if slot is not None else 0
            return (-crowd[placement[rid]], out)

        rid = sorted(placement, key=key)[0]
        host_id = placement[rid]
        t0 = self._clock()
        # placement out FIRST (the fleet's source of truth), actuation
        # after: during the drain the router still holds the slot, and
        # _sync_placement seeing a placement entry with no slot would
        # misread this deliberate retire as a replica loss and re-arm
        # the backfill clock
        with self._lock:
            self._placement.pop(rid, None)
            self.scale_downs += 1
        try:
            self.router.remove_replica(rid, drain=True)
        except MXNetError:
            pass   # already gone (raced a death) — the sync tick cleans up
        self._event("scale_down", host=host_id, replica=rid, reason=reason,
                    duration_s=round(self._clock() - t0, 3))
        return rid

    def _router_slots(self):
        with self.router._lock:
            return dict(self.router._slots)

    def _live_replicas(self):
        """Replicas this fleet placed that the router still serves."""
        from .router import DEAD
        slots = self._router_slots()
        with self._lock:
            placement = dict(self._placement)
        return [rid for rid in placement
                if rid in slots and slots[rid].state != DEAD]

    def _spawn_reason(self):
        """Why the next placer spawn happens: a pending backfill wins
        (capacity loss is the louder story), else the autoscaler's last
        decision."""
        with self._lock:
            if self._backfill_started is not None:
                return "backfill after capacity loss"
            return self._scale_reason or "reconcile to target"

    def _reconcile(self, reason=None):
        """Spawn until the live count meets the target (initial
        placement and post-loss backfill share this one path)."""
        guard = 0
        while not self._closed.is_set():
            live = len(self._live_replicas())
            if live >= self.target:
                break
            if reason is None:
                reason = self._spawn_reason()
            guard += 1
            if guard > 2 * self.autoscaler.max_replicas + 4:
                break   # spawns keep dying — breakers/events tell why
            try:
                self._spawn_one(reason)
            except MXNetError:
                if not self._live_hosts():
                    break
                self._closed.wait(min(self.tick_s, 0.2))
        live_now = len(self._live_replicas())
        with self._lock:
            # one lock hold for the whole completion decision: a
            # concurrent scale-down cancels the measurement by nulling
            # _backfill_started together with lowering target, and a
            # split read could pair the stale start with the shrunken
            # target and report a backfill that never happened
            started = self._backfill_started
            if started is None or live_now < self.target:
                return
            latency = self._clock() - started
            self._backfill_started = None
            self.backfills += 1
            self.last_backfill_s = round(latency, 3)
        self._event("backfill_complete", target=self.target,
                    latency_s=round(latency, 3))

    # -- host liveness --------------------------------------------------------
    def _probe_loop(self, hs):
        """One host's heartbeat thread: its beats feed the membership
        table regardless of how long any OTHER host's failing probe
        blocks.  The probe itself never judges death — only silence in
        the table past the deadline does (`_check_hosts`, on the
        control loop)."""
        host_id = hs.handle.host_id
        while not self._closed.wait(self.host_heartbeat_s):
            try:
                _faults.fire("host.down", host=host_id)
                hs.handle.heartbeat()
            except Exception:
                with self._lock:
                    hs.hb_failures += 1
                continue
            # membership beat BEFORE flipping alive: the watch loop
            # judges by (alive AND rank-in-dead-view), and alive=True
            # against a still-stale view would let _on_host_down
            # re-fire on a rejoining host (double-counted hosts_lost,
            # a phantom instant backfill)
            self.membership.heartbeat(hs.rank, self.membership.epoch,
                                      label=host_id)
            with self._lock:
                hs.beats += 1
                hs.hb_failures = 0
                was_dead = not hs.alive
                hs.alive = True
            if was_dead:
                self._event("host_rejoined", host=host_id)

    def _check_hosts(self):
        view = self.membership.view()
        with self._lock:
            hosts = list(self._hosts.values())
        for hs in hosts:
            if hs.rank in view["dead"] and hs.alive:
                self._on_host_down(hs, view["age"].get(hs.rank))

    def _on_host_down(self, hs, age_s):
        """A dead HOST kills all its replicas at once: fail them over
        immediately, drop them from the fleet, and backfill on the
        survivors.  The placement drop is ATOMIC (one lock hold for
        every replica on the host): the placer runs concurrently, and a
        one-at-a-time sweep would let it observe a live count that
        still includes a not-yet-removed dead replica — enough to
        declare a backfill complete that hasn't happened."""
        host_id = hs.handle.host_id
        # re-read the CURRENT view: _check_hosts judged from a
        # snapshot, and a rejoining host beats the table BEFORE its
        # alive flag flips — so a host that is alive again by now is
        # out of the fresh dead list and must not be re-declared
        if hs.rank not in self.membership.view()["dead"]:
            return
        with self._lock:
            if not hs.alive:
                return
            hs.alive = False
            self.hosts_lost += 1
            if self._backfill_started is None:
                self._backfill_started = self._clock()
            lost = [rid for rid, hid in self._placement.items()
                    if hid == host_id]
            for rid in lost:
                self._placement.pop(rid, None)
        # event BEFORE the router sweep: the declaration is the fact,
        # the removals its consequence — and the placer can finish the
        # whole backfill while the sweep runs, so anyone observing
        # backfills >= 1 must already see the host_down that caused it
        reason = (f"heartbeat silence {age_s:.1f}s > deadline "
                  f"{self.host_deadline_s:g}s"
                  if age_s is not None else "heartbeat silence")
        self._event("host_down", host=host_id, reason=reason,
                    replicas=len(lost))
        _faults.note("host_lost", site="host.down", host=host_id,
                     replicas=len(lost))
        for rid in lost:
            self.router.declare_lost(rid)
            try:
                self.router.remove_replica(rid, drain=False)
            except MXNetError:
                pass

    def _sync_placement(self):
        """Garbage-collect replicas the router declared dead on its own
        (individual replica death, not host death) so the live count —
        and therefore backfill — sees the capacity loss."""
        from .router import DEAD
        slots = self._router_slots()
        with self._lock:
            placement = dict(self._placement)
        for rid, host_id in placement.items():
            slot = slots.get(rid)
            if slot is not None and slot.state != DEAD:
                continue
            if slot is not None:
                try:
                    self.router.remove_replica(rid, drain=False)
                except MXNetError:
                    pass
            with self._lock:
                self._placement.pop(rid, None)
                if self._backfill_started is None:
                    self._backfill_started = self._clock()
            self._event("replica_lost", host=host_id, replica=rid)

    # -- the control loops ----------------------------------------------------
    def _watch_loop(self):
        """Liveness + autoscale DECISIONS only — never blocks on a
        spawn or a drain, so a host death is declared (and its replicas
        failed over at once) even while the placer is minutes deep in a
        cold spawn."""
        while not self._closed.wait(self.tick_s):
            try:
                self._check_hosts()
                self._sync_placement()
                self._autoscale_tick()
            except Exception as exc:   # the loop must outlive any tick
                self._event("tick_error",
                            reason=f"{type(exc).__name__}: {exc}")

    def _place_loop(self):
        """Actuation: reconcile the fleet toward target (spawns for
        initial placement growth and backfill, retires for surplus)."""
        while not self._closed.wait(self.tick_s):
            try:
                self._retire_surplus()
                self._reconcile()
            except Exception as exc:
                self._event("tick_error",
                            reason=f"{type(exc).__name__}: {exc}")

    def _retire_surplus(self):
        with self._lock:
            reason = self._scale_reason
        while not self._closed.is_set():
            if len(self._live_replicas()) <= self.target:
                break
            if self._retire_one(reason or "scale-down") is None:
                break

    def _autoscale_tick(self):
        wait_s = self.router.estimated_wait_s()
        est_ms = None if wait_s is None else wait_s * 1e3
        with self._lock:
            self._last_signal_ms = est_ms
        live = self._live_replicas()
        slots = self._router_slots()
        busy = any(slots[rid].replica.outstanding() > 0
                   for rid in live if rid in slots)
        action, reason = self.autoscaler.observe(est_ms, len(live), busy)
        if action == "up":
            # grow to at least live+1 but NEVER below the current
            # target: mid-backfill (live transiently under target after
            # a host loss) a scale-up must not shrink the backfill goal.
            # The PLACER does the spawning — a decision is instant, an
            # actuation can block for minutes.
            with self._lock:
                self.target = min(max(self.target, len(live) + 1),
                                  self.autoscaler.max_replicas)
                self._scale_reason = reason
        elif action == "down":
            with self._lock:
                self.target = max(len(live) - 1,
                                  self.autoscaler.min_replicas)
                self._scale_reason = reason
                # an intervening scale-down invalidates a pending
                # backfill measurement: without this, target meeting
                # the SHRUNKEN live count would report a successful
                # "backfill" (with idle-period latency) that never
                # happened
                self._backfill_started = None

    # -- observability --------------------------------------------------------
    def _event(self, action, **ctx):
        entry = _note_event(self.name, action,
                            t=round(self._clock(), 3), **ctx)
        with self._lock:
            self._events.append(entry)

    def stats(self):
        """Fleet snapshot: per-host replica counts + liveness, the
        placement map, scale events with reasons, backfill latency, and
        the autoscaler's live signal/streaks — the KVStore/router
        stats() convention."""
        view = self.membership.view()
        with self._lock:
            placement = dict(self._placement)
            events = list(self._events)
            snap = {
                "fleet": self.name,
                "target": self.target,
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "hosts_lost": self.hosts_lost,
                "backfills": self.backfills,
                "spawn_failures": self.spawn_failures,
                "backfill_latency_s": self.last_backfill_s,
                "signal": {
                    "est_wait_ms": self._last_signal_ms,
                    "slo_ms": self.autoscaler.slo_ms,
                    "clamped_at_max": self.autoscaler.clamped_at_max,
                    "clamped_at_min": self.autoscaler.clamped_at_min,
                    "cooldown_remaining_s": round(
                        self.autoscaler.cooldown_remaining_s(), 3),
                    **{k: round(v, 3)
                       for k, v in self.autoscaler.streaks().items()},
                },
            }
            hosts = {}
            for hid, hs in self._hosts.items():
                hosts[hid] = {
                    "alive": hs.alive,
                    "replicas": sum(1 for h in placement.values()
                                    if h == hid),
                    "beats": hs.beats,
                    "hb_failures": hs.hb_failures,
                    "age_s": view["age"].get(hs.rank),
                    "spawn_breaker": hs.breaker.state,
                }
        snap["live_replicas"] = len(self._live_replicas())
        snap["hosts"] = hosts
        snap["placement"] = placement
        snap["events"] = events[-32:]
        return snap

    def scrape(self):
        """The fleet-wide telemetry aggregate: this process's registry
        (router, fleet, serving.* producers), every live host daemon's
        snapshot, and every placed remote replica's worker snapshot —
        one call, the whole fleet.  Dead or unreachable legs are
        recorded under ``unreachable`` instead of failing the scrape
        (a half-dead fleet is exactly when you need the numbers)."""
        from ..obs.scrape import metrics_reply
        local = metrics_reply()
        out = {"fleet": self.name,
               "local": {"values": local["values"],
                         "prom": local["prom"]},
               "hosts": {}, "replicas": {}, "unreachable": []}
        with self._lock:
            hosts = {hid: hs.handle for hid, hs in self._hosts.items()}
        for hid, handle in hosts.items():
            try:
                snap = handle.scrape()
            except Exception:
                out["unreachable"].append(f"host:{hid}")
                continue
            if snap is not None:
                out["hosts"][hid] = snap
        for rid, slot in self._router_slots().items():
            scrape_fn = getattr(slot.replica, "scrape", None)
            if scrape_fn is None:
                continue
            try:
                out["replicas"][rid] = scrape_fn()
            except Exception:
                out["unreachable"].append(f"replica:{rid}")
        return out
