"""Fleet host daemon: one agent process per serving host.

``python -m incubator_mxnet_tpu.serving.hostd --host-id host-a`` prints
``HOSTD_PORT <n>`` / ``HOSTD_READY`` on stdout and serves the fleet
host protocol over the same length-prefixed transport frames as the
parameter server and the replica workers:

* ``hb``    — host liveness + load (live worker count, pid).  The
  `FleetManager` feeds these beats into its `dist.membership` table;
  silence past the deadline is host death.
* ``spawn`` — launch one `serving.worker` ON THIS HOST from a
  `ReplicaSpec` message (the worker binds this daemon's address, so
  the router connects across the network, not to localhost) and wait
  for its readiness handshake; the reply carries the worker's port and
  its ``REPLICA_READY`` evidence (programs/compiles/disk_hits — the
  fleet's zero-compile warm-spinup cert).
* ``stop``  — kill every worker, then exit.  (Individual worker
  lifecycle belongs to the worker's own control channel — the router's
  drain/close path stops it directly and the daemon's heartbeat reap
  collects the exit.)

The daemon and its workers share one process group
(`AgentHost.launch_local` starts it with ``start_new_session=True``),
so SIGKILLing the group is a faithful whole-host power-off: daemon and
workers die together, exactly the failure the fleet's membership
deadline + backfill path exist to survive (`tools/run_chaos.py
--fleet` drives that weapon).
"""
from __future__ import annotations

import argparse
import os
import socketserver
import sys
import threading

from ..analysis import locks as _locks
from ..obs import metrics as _obs_metrics
from ..obs import trace as _obs_trace

__all__ = ["HostDaemon", "main"]


class HostDaemon:
    """The serving loop around one host's worker population."""

    def __init__(self, host_id, host="127.0.0.1", port=0):
        self.host_id = str(host_id)
        self.host = str(host)
        self._lock = _locks.make_lock("serving.hostd")
        self._workers = {}    # replica_id -> {"proc", "port", "ready"}
        self._spawning = {}   # replica_id -> Event (first spawn running)
        self.spawns = 0
        _obs_metrics.register_producer("hostd", self._obs_stats)
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                from ..dist.transport import recv_msg, send_msg
                while True:
                    try:
                        msg = recv_msg(self.request)
                    except (EOFError, ConnectionError, OSError):
                        break
                    try:
                        reply = outer._handle(msg)
                    except Exception as exc:
                        reply = {"error": f"hostd dispatch failed: {exc}",
                                 "seq": msg.get("seq")}
                    try:
                        send_msg(self.request, reply)
                    except (ConnectionError, OSError):
                        break
                    if msg.get("cmd") == "stop":
                        outer._kill_workers()
                        # os._exit skips atexit: flush buffered spans
                        _obs_trace.flush()
                        os._exit(0)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((self.host, int(port)), Handler)
        self.port = self._server.server_address[1]

    # -- command dispatch ----------------------------------------------------
    def _reap_locked(self):
        for rid in list(self._workers):
            proc = self._workers[rid]["proc"]
            if proc.poll() is not None:
                del self._workers[rid]

    def _obs_stats(self):
        with self._lock:
            self._reap_locked()
            return {"workers": len(self._workers), "spawns": self.spawns}

    def _handle(self, msg):
        cmd = msg.get("cmd")
        seq = msg.get("seq")
        if cmd == "hb":
            with self._lock:
                self._reap_locked()
                return {"ok": True, "host_id": self.host_id,
                        "workers": len(self._workers),
                        "pid": os.getpid(), "seq": seq}
        if cmd == "metrics":
            from ..obs.scrape import metrics_reply
            return metrics_reply(seq=seq)
        if cmd == "spawn":
            with _obs_trace.server_span(msg, "hostd.spawn", cat="fleet",
                                        replica=msg.get("replica_id")):
                return dict(self._spawn(msg), seq=seq)
        if cmd == "stop":
            return {"ok": True, "seq": seq}
        return {"error": f"hostd: unknown cmd {cmd!r}", "seq": seq}

    def _worker_reply(self, rec):
        return {"ok": True, "port": rec["port"], "ready": rec["ready"],
                "pid": rec["proc"].pid}

    def _spawn(self, msg):
        from .fleet import ReplicaSpec
        from .replica import launch_worker, worker_argv
        spec = ReplicaSpec.from_msg(msg["spec"])
        rid = msg.get("replica_id") or spec.name
        # IDEMPOTENT by replica id, like the worker's rid dedup: a
        # timed-out / lost reply makes the channel RESEND the spawn
        # request on a fresh connection, and a second worker for the
        # same rid would be an orphan nobody ever stops.  A live worker
        # answers with ITS endpoint; a resend racing the first spawn
        # waits for it instead of double-launching.
        while True:
            with self._lock:
                self._reap_locked()
                rec = self._workers.get(rid)
                if rec is not None:
                    return self._worker_reply(rec)
                pending = self._spawning.get(rid)
                if pending is None:
                    self._spawning[rid] = threading.Event()
                    break
            pending.wait(600)
        try:
            # the worker binds THIS host's address so the router's
            # channels cross the network — the 127.0.0.1 assumption
            # dies here
            cmd = worker_argv(prefix=spec.prefix, epoch=spec.epoch,
                              symbol_file=spec.symbol_file,
                              checkpoint_dir=spec.checkpoint_dir,
                              data_shapes=spec.data_shapes,
                              buckets=spec.buckets, name=spec.name,
                              host=self.host)
            proc, port, ready = launch_worker(cmd, env=spec.env,
                                              name=spec.name, tag=rid)
            with self._lock:
                rec = self._workers[rid] = {"proc": proc, "port": port,
                                            "ready": ready}
                self.spawns += 1
        finally:
            with self._lock:
                ev = self._spawning.pop(rid, None)
            if ev is not None:
                ev.set()
        return self._worker_reply(rec)

    def _kill_workers(self):
        with self._lock:
            workers, self._workers = dict(self._workers), {}
        for rec in workers.values():
            try:
                rec["proc"].kill()
            except Exception:
                pass

    def serve_forever(self):
        self._server.serve_forever(poll_interval=0.1)

    def start(self):
        self._thread = threading.Thread(target=self.serve_forever,
                                        daemon=True,
                                        name="mx-hostd-server")
        self._thread.start()
        return self

    def shutdown(self):
        self._kill_workers()
        self._server.shutdown()
        self._server.server_close()


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="serving.hostd", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--host-id", required=True,
                    help="this host's fleet registry name")
    ap.add_argument("--host", default="127.0.0.1",
                    help="address the daemon AND its workers bind")
    ap.add_argument("--port", type=int, default=0)
    args = ap.parse_args(argv)
    daemon = HostDaemon(args.host_id, host=args.host, port=args.port)
    print("HOSTD_PORT %d" % daemon.port, flush=True)
    print("HOSTD_READY host_id=%s" % daemon.host_id, flush=True)
    daemon.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
