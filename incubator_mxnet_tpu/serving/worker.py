"""Replica worker process: one served model behind a transport endpoint.

``python -m incubator_mxnet_tpu.serving.worker --prefix model --epoch 3
--data-shapes data=1,784 --buckets 1,4,16`` loads the model, warms the
bucket ladder (from the shared ``MXNET_PROGRAM_CACHE_DIR`` disk tier
when one is configured — replica fleet spin-up is then zero-compile),
prints ``REPLICA_PORT <n>`` / ``REPLICA_READY`` on stdout, and serves
the replica control protocol over the same length-prefixed frames as
the parameter server:

* ``infer``  — run one request through the bucket ladder.  Deduplicated
  by the ROUTER's request id: a resend of an rid this worker already
  executed replays the cached outputs instead of executing twice (the
  router's no-duplicate-execution guarantee at the worker boundary).
* ``hb``     — cheap liveness + load (`outstanding`, weight `version`).
* ``probe``  — deepcheck: a real bucket-1 inference.
* ``swap``   — reload parameters from the newest valid checkpoint under
  a directory; same shapes, same programs, zero XLA compiles.
* ``stats``  — metrics snapshot + executed-rid diagnostics (bounded).
* ``stop``   — drain and exit.

The handler is deliberately single-model and thread-per-connection
(`ThreadingTCPServer`): the router owns spreading and batching policy;
a worker just executes.
"""
from __future__ import annotations

import argparse
import collections
import os
import socketserver
import sys
import threading

import numpy as _np

from ..analysis import locks as _locks
from ..analysis import tsan as _tsan
from ..obs import metrics as _obs_metrics
from ..obs import trace as _obs_trace
from .model import ServedModel

__all__ = ["ReplicaWorker", "main"]


class ReplicaWorker:
    """The serving loop around one `ServedModel`."""

    def __init__(self, model, host="127.0.0.1", port=0, dedup_window=16384):
        self.model = model
        self.version = 0
        self._lock = _locks.make_lock("serving.worker")
        _tsan.instrument(self, "serving.worker")
        # telemetry plane: this worker's counters under the 'worker'
        # namespace, served by the 'metrics' frame below
        _obs_metrics.register_producer("worker", self._obs_stats)
        self._outstanding = 0
        self._executed = 0
        self._dedup_hits = 0
        # rid -> outputs, bounded: the idempotency window only needs to
        # cover the router's failover horizon, not a week of traffic
        self._done = collections.OrderedDict()
        self._done_cap = int(dedup_window)
        self._executed_rids = collections.deque(maxlen=self._done_cap)
        # rid -> Event for executions still in flight: a transport
        # resend of a rid the worker is CURRENTLY executing must wait
        # and replay, not execute a second time
        self._running = {}
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                from ..dist.transport import recv_msg, send_msg
                while True:
                    try:
                        msg = recv_msg(self.request)
                    except (EOFError, ConnectionError, OSError):
                        break
                    try:
                        reply = outer._handle(msg)
                    except Exception as exc:
                        reply = {"error": f"replica dispatch failed: "
                                          f"{exc}", "seq": msg.get("seq")}
                    try:
                        send_msg(self.request, reply)
                    except (ConnectionError, OSError):
                        break
                    if msg.get("cmd") == "stop":
                        # os._exit skips atexit: flush buffered spans
                        # first or the merged trace loses this worker
                        _obs_trace.flush()
                        os._exit(0)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = None

    def _obs_stats(self):
        with self._lock:
            return {"executed": self._executed,
                    "dedup_hits": self._dedup_hits,
                    "outstanding": self._outstanding,
                    "version": self.version,
                    "programs": self.model.program_count()}

    # -- command dispatch ----------------------------------------------------
    def _handle(self, msg):
        cmd = msg.get("cmd")
        seq = msg.get("seq")
        if cmd == "infer":
            # the cross-process trace edge: adopt the router's span
            # context from the frame so this execution is a CHILD of
            # the dispatch that sent it
            with _obs_trace.server_span(msg, "worker.infer",
                                        cat="serving",
                                        rid=msg.get("rid")):
                return dict(self._infer(msg), seq=seq)
        if cmd == "metrics":
            from ..obs.scrape import metrics_reply
            return metrics_reply(seq=seq)
        if cmd == "hb":
            with self._lock:
                out = {"ok": True, "outstanding": self._outstanding,
                       "version": self.version}
            return dict(out, seq=seq)
        if cmd == "probe":
            model = self.model
            inputs = [_np.zeros((1,) + model._sample_shapes[n],
                                model._dtype) for n in model.data_names]
            model.infer(inputs)
            return {"ok": True, "programs": model.program_count(),
                    "version": self.version, "seq": seq}
        if cmd == "swap":
            # the `replica.swap` fault site fires ROUTER-side (it covers
            # local and remote replicas uniformly); the worker just
            # executes the reload
            from .replica import _load_checkpoint_params
            args, auxs = _load_checkpoint_params(msg["checkpoint_dir"])
            self.model.set_params(args, auxs)
            with self._lock:
                # handler threads are per-connection: the version bump
                # must hold the same lock the hb/stats readers take
                # (mxtsan: shared-state-race on a lock-free increment)
                self.version += 1
                version = self.version
            return {"ok": True, "version": version,
                    "programs": self.model.program_count(), "seq": seq}
        if cmd == "stats":
            from .. import compile as _compile
            try:
                cache = _compile.stats()["counters"]
            except Exception:
                cache = None
            with self._lock:
                return {"ok": True, "executed": self._executed,
                        "dedup_hits": self._dedup_hits,
                        "version": self.version,
                        "programs": self.model.program_count(),
                        "executed_rids": list(self._executed_rids),
                        "cache": cache,
                        "seq": seq}
        if cmd == "stop":
            return {"ok": True, "seq": seq}
        return {"error": f"replica worker: unknown cmd {cmd!r}", "seq": seq}

    def _infer(self, msg):
        rid = msg.get("rid")
        while True:
            with self._lock:
                if rid is not None and rid in self._done:
                    # idempotent resend: replay, never re-execute
                    self._dedup_hits += 1
                    return {"ok": True, "outs": self._done[rid],
                            "deduped": True}
                running = self._running.get(rid) \
                    if rid is not None else None
                if running is None:
                    if rid is not None:
                        self._running[rid] = threading.Event()
                    self._outstanding += 1
                    break
            # a resend raced a still-executing first copy: wait for it
            # and replay its result (re-checking — if the first attempt
            # FAILED, this one takes over and executes)
            running.wait(timeout=600)
        try:
            outs = self.model.infer(msg["inputs"])
            outs = [o.asnumpy() for o in outs]
        except Exception:
            with self._lock:
                self._outstanding -= 1
                ev = self._running.pop(rid, None)
            if ev is not None:
                ev.set()   # a waiting resend retries the execution
            raise
        with self._lock:
            self._outstanding -= 1
            self._executed += 1
            if rid is not None:
                self._executed_rids.append(rid)
                self._done[rid] = outs
                while len(self._done) > self._done_cap:
                    self._done.popitem(last=False)
                ev = self._running.pop(rid, None)
            else:
                ev = None
        if ev is not None:
            ev.set()
        return {"ok": True, "outs": outs}

    def serve_forever(self):
        self._server.serve_forever(poll_interval=0.1)

    def start(self):
        self._thread = threading.Thread(target=self.serve_forever,
                                        daemon=True,
                                        name="mx-replica-worker-server")
        self._thread.start()
        return self

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()


def _parse_shapes(spec):
    shapes = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        name, _, dims = part.partition("=")
        shapes.append((name, tuple(int(d) for d in dims.split(",") if d)))
    if not shapes:
        raise SystemExit("worker: --data-shapes required "
                         "(name=d0,d1[;name=...])")
    return shapes


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="serving.worker", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--name", default="model")
    ap.add_argument("--prefix", default=None,
                    help="classic checkpoint pair prefix")
    ap.add_argument("--epoch", type=int, default=0)
    ap.add_argument("--symbol-file", default=None)
    ap.add_argument("--checkpoint-dir", default=None,
                    help="elastic checkpoint dir (needs --symbol-file)")
    ap.add_argument("--data-shapes", required=True,
                    metavar="name=d0,d1[;name=...]")
    ap.add_argument("--buckets", default="1,2,4,8")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--host", default="127.0.0.1")
    args = ap.parse_args(argv)

    shapes = _parse_shapes(args.data_shapes)
    buckets = tuple(int(b) for b in args.buckets.split(",") if b)
    common = dict(data_shapes=shapes, buckets=buckets, name=args.name)
    if args.prefix is not None:
        model = ServedModel.load(args.prefix, args.epoch, **common)
    elif args.checkpoint_dir is not None:
        if args.symbol_file is None:
            raise SystemExit("worker: --checkpoint-dir needs --symbol-file")
        model = ServedModel.from_checkpoint_dir(
            args.symbol_file, args.checkpoint_dir, **common)
    else:
        raise SystemExit("worker: --prefix or --checkpoint-dir required")

    worker = ReplicaWorker(model, host=args.host, port=args.port)
    print("REPLICA_PORT %d" % worker.port, flush=True)
    # warm AFTER the port is known so a spawning router can already
    # connect; with a shared MXNET_PROGRAM_CACHE_DIR the ladder loads
    # from disk — zero XLA compiles for every replica after the first
    model.warmup()
    from .. import compile as _compile
    try:
        c = _compile.stats()["counters"]
        cache_note = " compiles=%d disk_hits=%d" % (c["compiles"],
                                                    c["disk_hits"])
    except Exception:
        cache_note = ""
    print("REPLICA_READY programs=%d%s" % (model.program_count(),
                                           cache_note), flush=True)
    worker.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
