"""Continuous-batching autoregressive decode engine.

The stateless serving plane (`ServedModel` + `MicroBatcher`) answers a
request with one program dispatch.  An LM request is different: it
holds STATE (its KV cache) across hundreds of dispatches.  Waiting for
a full batch and decoding it in lockstep ("static batching") leaves
every finished-early slot idle until the longest sequence completes —
the aggregate-tokens/s gap `tools/run_lm_bench.py` measures.  This
engine decodes continuously instead:

* a fixed pool of **slots** (rows of the fixed-shape KV cache);
* every tick runs ONE decode-step program advancing all occupied
  slots by one token;
* finished sequences (EOS / token budget / cache full) are evicted
  between ticks and their slots immediately re-admitted from the
  queue via a bucketed **prefill** (one compiled signature per prompt
  bucket on the seq-length ladder);
* admission is budgeted per tick (`MXNET_DECODE_ADMIT_PER_TICK`), so
  a burst of long prefills never stalls the decode tick for the
  sequences already running.

Shape discipline buys the zero-recompile guarantee: the decode step's
signature is fixed at warmup and prompts are padded onto the bucket
ladder, so the steady state never presents XLA a new shape no matter
how requests arrive or finish (`analysis.recompile` audits this; the
`kv-cache-recompile` mxlint pass flags the unbucketed antipattern in
user code).  The KV cache rides as a donated carry through both
programs — one HBM copy total.

`DecodeReplica` wraps the engine in the `Replica` contract, so the
existing `ReplicaRouter` gives LM serving the same failure story as
the stateless plane: a replica SIGKILLed mid-decode fails its
in-flight futures with `ReplicaLostError`, the router replays the
full request (prompt + budget — the prefill re-derives the lost KV
state) on a survivor, and the completed-rid fence keeps any answer
from being delivered twice.  The fleet `Autoscaler` needs no changes:
it watches `estimated_wait_s()`, which the engine derives from queue
depth and the measured per-tick token rate.
"""
from __future__ import annotations

import threading
import time

from concurrent.futures import Future

import numpy as _np

from ..analysis import locks as _locks
from ..base import MXNetError
from .metrics import ServingMetrics
from .replica import Replica, ReplicaLostError
from .router import PRIORITIES

__all__ = ["DecodeEngine", "DecodeReplica", "DEFAULT_PROMPT_BUCKETS"]

DEFAULT_PROMPT_BUCKETS = (8, 16, 32)

_RANK_TO_CLASS = dict(enumerate(PRIORITIES))


def _knob(name, default):
    from .. import config as _config
    try:
        v = _config.get(name)
    except Exception:
        v = None
    return default if v in (None, "") else v


def _norm_priority(priority):
    """Router dispatch passes PRIORITY_RANK ints; direct callers pass
    class names.  Normalize to the class string."""
    if isinstance(priority, str):
        if priority not in PRIORITIES:
            raise MXNetError(f"decode: unknown priority {priority!r}")
        return priority
    return _RANK_TO_CLASS.get(int(priority), "batch")


class _Slot:
    """Host-side state of one cache row."""
    __slots__ = ("rid", "generated", "remaining", "future", "cls",
                 "t_submit", "pos", "last_token")

    def __init__(self, rid, first_token, prompt_len, max_new, future,
                 cls, t_submit):
        self.rid = rid
        self.generated = [int(first_token)]
        self.remaining = int(max_new) - 1
        self.future = future
        self.cls = cls
        self.t_submit = t_submit
        self.pos = int(prompt_len)      # where the NEXT K/V row lands
        self.last_token = int(first_token)


class _Pending:
    __slots__ = ("rid", "tokens", "max_new", "cls", "future", "t_submit",
                 "seq")

    def __init__(self, rid, tokens, max_new, cls, future, t_submit, seq):
        self.rid = rid
        self.tokens = tokens
        self.max_new = max_new
        self.cls = cls
        self.future = future
        self.t_submit = t_submit
        self.seq = seq


class DecodeEngine:
    """Continuous batching over one LM's decode programs.

    Parameters
    ----------
    cfg : llm.LMConfig
    arg_params : dict name -> array (the trained Module/gluon params)
    slots : cache rows decoded per tick (MXNET_DECODE_SLOTS)
    buckets : prompt-length ladder (MXNET_DECODE_BUCKETS)
    """

    def __init__(self, cfg, arg_params, slots=None, buckets=None,
                 name="lm", metrics=None, admit_per_tick=None,
                 max_new_default=None, start=True):
        from ..llm import DecodePrograms, stack_lm_params
        self.cfg = cfg
        self.name = name
        self.slots = int(slots if slots is not None
                         else _knob("MXNET_DECODE_SLOTS", 8))
        if buckets is None:
            raw = _knob("MXNET_DECODE_BUCKETS", "")
            buckets = tuple(int(x) for x in str(raw).split(",") if x) \
                or DEFAULT_PROMPT_BUCKETS
        self.buckets = tuple(sorted(int(b) for b in buckets))
        if self.buckets[-1] > cfg.max_len:
            raise MXNetError(
                "decode: largest prompt bucket %d exceeds max_len %d"
                % (self.buckets[-1], cfg.max_len))
        self.admit_per_tick = int(
            admit_per_tick if admit_per_tick is not None
            else _knob("MXNET_DECODE_ADMIT_PER_TICK", 2))
        self.max_new_default = int(
            max_new_default if max_new_default is not None
            else _knob("MXNET_DECODE_MAX_NEW", 32))
        self.metrics = metrics or ServingMetrics(name)
        self.programs = DecodePrograms(cfg, stack_lm_params(arg_params, cfg),
                                       label=name)
        # telemetry plane: this engine's stats() under the stable
        # 'decode' namespace (weakref'd — a closed engine drops out)
        from ..obs import metrics as _obs_metrics
        _obs_metrics.register_producer("decode.%s" % name, self.stats)
        self._audit_key = "decode:%s" % name
        self._lock = _locks.make_lock("serving.decode")
        self._work = threading.Condition(self._lock)
        self._queue = []            # sorted pending list (rank, seq)
        self._seq = 0
        self._slots = [None] * self.slots   # _Slot | None
        self._ck = self._cv = None
        self._dead = False
        self._draining = False
        self._executed_rids = []
        self.ticks = 0
        self.tokens_generated = 0
        self.admitted = 0
        self.evicted = 0
        self.rejected = 0
        self._tick_s_ewma = None
        self.warmed = False
        self._thread = None
        if start:
            self.warmup()
            self.start()

    # -- lifecycle -----------------------------------------------------------
    def warmup(self):
        """Compile the full program ladder up front and stand up the
        live cache.  Every signature is pre-declared with the recompile
        auditor, so post-warmup novelty is a real finding."""
        import jax.numpy as jnp
        from .. import fused as _fused
        from ..analysis import recompile as _recompile
        from ..llm import init_kv_cache
        for b in self.buckets:
            _recompile.register(self._audit_key, ("tokens",),
                                ((("1x%d" % b), "int32"),))
        _recompile.register(self._audit_key, ("tokens",),
                            ((("step%d" % self.slots), "int32"),))
        compiles = self.programs.warmup(self.slots, self.buckets)
        ck, cv = init_kv_cache(self.cfg, self.slots)
        self._ck, self._cv = _fused.reown_for_donation((ck, cv))
        self._tokens_buf = jnp.zeros((self.slots,), jnp.int32)
        self.warmed = True
        return compiles

    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop,
                                        name="mx-decode-%s" % self.name,
                                        daemon=True)
        self._thread.start()

    def close(self, drain=True):
        with self._lock:
            if self._dead:
                return
            if drain:
                self._draining = True
                self._work.notify_all()
        if drain and self._thread is not None:
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                with self._lock:
                    if not self._queue and not any(self._slots):
                        break
                time.sleep(0.01)
        self._shutdown(ReplicaLostError(self.name, reason="engine closed"))

    def kill(self):
        """SIGKILL semantics: every queued and in-flight sequence fails
        with `ReplicaLostError` NOW — the router's failover trigger."""
        self._shutdown(ReplicaLostError(self.name,
                                        reason="decode engine killed"))

    def _shutdown(self, exc):
        with self._lock:
            if self._dead:
                return
            self._dead = True
            pending = list(self._queue)
            self._queue.clear()
            active = [s for s in self._slots if s is not None]
            self._slots = [None] * self.slots
            self._work.notify_all()
        for p in pending:
            if not p.future.done():
                p.future.set_exception(
                    ReplicaLostError(self.name, rid=p.rid,
                                     reason=str(exc)))
        for s in active:
            if not s.future.done():
                s.future.set_exception(
                    ReplicaLostError(self.name, rid=s.rid,
                                     reason=str(exc)))
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(10.0)

    # -- intake --------------------------------------------------------------
    def bucket_for(self, n):
        for b in self.buckets:
            if n <= b:
                return b
        return None

    def submit(self, tokens, max_new_tokens=None, rid=None,
               priority="interactive", timeout_ms=None):
        """Queue one sequence; returns a Future resolving to
        ``{"rid", "tokens"}`` (the generated continuation)."""
        del timeout_ms   # admission control is the router's job
        cls = _norm_priority(priority)
        tokens = [int(t) for t in _np.asarray(tokens).reshape(-1)]
        max_new = int(max_new_tokens if max_new_tokens is not None
                      else self.max_new_default)
        bucket = self.bucket_for(len(tokens))
        fut = Future()
        if not tokens or bucket is None \
                or len(tokens) + max_new > self.cfg.max_len:
            self.rejected += 1
            self.metrics.record_reject()
            fut.set_exception(MXNetError(
                "decode '%s': prompt of %d tokens (+%d new) does not fit "
                "the ladder (buckets %s, max_len %d)"
                % (self.name, len(tokens), max_new, self.buckets,
                   self.cfg.max_len)))
            return fut
        with self._lock:
            if self._dead:
                raise ReplicaLostError(self.name, rid=rid,
                                       reason="decode engine is down")
            if self._draining:
                raise MXNetError(
                    "decode '%s': draining, not accepting" % self.name)
            self._seq += 1
            if rid is None:
                rid = "%s/seq-%d" % (self.name, self._seq)
            p = _Pending(rid, tokens, max_new, cls, fut, time.monotonic(),
                         self._seq)
            rank = PRIORITIES.index(cls)
            at = len(self._queue)
            for i, q in enumerate(self._queue):
                if (PRIORITIES.index(q.cls), q.seq) > (rank, p.seq):
                    at = i
                    break
            self._queue.insert(at, p)
            self.metrics.record_request(
                len(self._queue) + sum(1 for s in self._slots if s))
            self._work.notify_all()
        return fut

    # -- engine loop ---------------------------------------------------------
    def _loop(self):
        while True:
            with self._lock:
                while not self._dead and not self._queue \
                        and not any(s is not None for s in self._slots):
                    self._work.wait(0.5)
                if self._dead:
                    return
            try:
                self.step()
            except Exception as exc:   # a broken program is engine death
                self._shutdown(ReplicaLostError(
                    self.name, reason="decode tick failed: %r" % (exc,)))
                return

    def step(self):
        """One engine tick: admit into free slots, then advance every
        occupied slot one token and evict the finished."""
        t0 = time.monotonic()
        self._admit()
        n = self._decode_tick()
        dt = time.monotonic() - t0
        if n:
            self._tick_s_ewma = dt if self._tick_s_ewma is None \
                else 0.9 * self._tick_s_ewma + 0.1 * dt
        self.ticks += 1
        return n

    def _admit(self):
        import jax.numpy as jnp
        from ..obs import trace as _obs_trace
        admitted = 0
        while admitted < self.admit_per_tick:
            with self._lock:
                if self._dead or not self._queue:
                    return
                free = next((i for i, s in enumerate(self._slots)
                             if s is None), None)
                if free is None:
                    return
                p = self._queue.pop(0)
            bucket = self.bucket_for(len(p.tokens))
            padded = _np.zeros((1, bucket), _np.int32)
            padded[0, :len(p.tokens)] = p.tokens
            t0 = time.monotonic()
            from ..analysis import recompile as _recompile
            _recompile.note(self._audit_key, ("tokens",),
                            ((("1x%d" % bucket), "int32"),))
            self._ck, self._cv, tok, _ = self.programs.prefill(
                self.programs.params, self._ck, self._cv,
                jnp.asarray(padded), jnp.int32(free),
                jnp.int32(len(p.tokens)))
            dur = time.monotonic() - t0
            if _obs_trace.enabled():
                _obs_trace.record_span(
                    "decode.prefill", ts_us=t0 * 1e6, dur_us=dur * 1e6,
                    cat="serving", rid=p.rid, bucket=bucket,
                    prompt_len=len(p.tokens))
            slot = _Slot(p.rid, int(tok), len(p.tokens), p.max_new,
                         p.future, p.cls, p.t_submit)
            with self._lock:
                if self._dead:
                    if not p.future.done():
                        p.future.set_exception(ReplicaLostError(
                            self.name, rid=p.rid, reason="killed"))
                    return
                self._slots[free] = slot
                self.admitted += 1
            self.metrics.record_batch(1, bucket, dur)
            admitted += 1
            if slot.remaining <= 0 or slot.last_token == self.cfg.eos_id \
                    or slot.pos + 1 >= self.cfg.max_len:
                self._evict(free)

    def _decode_tick(self):
        import jax.numpy as jnp
        from ..obs import trace as _obs_trace
        with self._lock:
            live = [(i, s) for i, s in enumerate(self._slots)
                    if s is not None]
        if not live:
            return 0
        tokens = _np.zeros((self.slots,), _np.int32)
        positions = _np.zeros((self.slots,), _np.int32)
        for i, s in live:
            tokens[i] = s.last_token
            positions[i] = s.pos
        t0 = time.monotonic()
        from ..analysis import recompile as _recompile
        _recompile.note(self._audit_key, ("tokens",),
                        ((("step%d" % self.slots), "int32"),))
        self._ck, self._cv, next_tokens, _ = self.programs.step(
            self.programs.params, self._ck, self._cv,
            jnp.asarray(tokens), jnp.asarray(positions))
        next_tokens = _np.asarray(next_tokens)
        dur = time.monotonic() - t0
        if _obs_trace.enabled():
            _obs_trace.record_span(
                "decode.step", ts_us=t0 * 1e6, dur_us=dur * 1e6,
                cat="serving", slots_active=len(live),
                slots_total=self.slots)
        self.metrics.record_batch(len(live), self.slots, dur)
        produced = 0
        for i, s in live:
            tok = int(next_tokens[i])
            s.generated.append(tok)
            s.last_token = tok
            s.pos += 1
            s.remaining -= 1
            produced += 1
            if s.remaining <= 0 or tok == self.cfg.eos_id \
                    or s.pos + 1 >= self.cfg.max_len:
                self._evict(i)
        self.tokens_generated += produced
        return produced

    def _evict(self, idx):
        with self._lock:
            s = self._slots[idx]
            self._slots[idx] = None
            if s is None:
                return
            self.evicted += 1
            self._executed_rids.append(s.rid)
            del self._executed_rids[:-4096]
        if not s.future.done():
            s.future.set_result({"rid": s.rid, "tokens": s.generated})
        self.metrics.record_response(time.monotonic() - s.t_submit,
                                     cls=s.cls)

    # -- load signals (router dispatch + fleet autoscaler) -------------------
    def outstanding(self):
        with self._lock:
            return len(self._queue) + sum(1 for s in self._slots if s)

    def estimated_wait_s(self):
        """Queue drain time at the measured tick rate — what the fleet
        `Autoscaler` compares against its SLO."""
        with self._lock:
            queued = len(self._queue)
            active = sum(1 for s in self._slots if s)
            tick = self._tick_s_ewma
        if tick is None or not (queued or active):
            return 0.0
        # a queued sequence waits for a slot (~avg remaining budget of
        # the active set) plus its own generation
        per_seq_ticks = float(self.max_new_default)
        backlog_ticks = per_seq_ticks * (queued / max(1, self.slots))
        return tick * backlog_ticks

    def stats(self):
        with self._lock:
            return {
                "name": self.name,
                "slots": self.slots,
                "slots_active": sum(1 for s in self._slots if s),
                "queue_depth": len(self._queue),
                "ticks": self.ticks,
                "tokens_generated": self.tokens_generated,
                "admitted": self.admitted,
                "evicted": self.evicted,
                "rejected": self.rejected,
                "programs": self.programs.program_count(),
                "compiles": self.programs.compile_count(),
                "tick_s_ewma": self._tick_s_ewma,
                "executed_rids": list(self._executed_rids),
                "dead": self._dead,
            }


class DecodeReplica(Replica):
    """`Replica`-contract face of one `DecodeEngine`, so `ReplicaRouter`
    (and through it the priority classes, shed thresholds, health loop
    and fleet autoscaler) drives LM decode exactly like stateless
    serving.  Requests are ``{"tokens": ..., "max_new_tokens": ...}``."""

    def __init__(self, cfg, arg_params, replica_id="decode0", **engine_kw):
        self.replica_id = str(replica_id)
        self.version = 0
        self._cfg = cfg
        self.engine = DecodeEngine(cfg, arg_params,
                                   name=self.replica_id, **engine_kw)
        self.ready_info = {"compiles": self.engine.programs.compile_count(),
                           "programs": self.engine.programs.program_count()}

    def submit(self, inputs, timeout_ms=None, rid=None, priority=1):
        if isinstance(inputs, dict):
            tokens = inputs.get("tokens")
            max_new = inputs.get("max_new_tokens")
        else:
            tokens, max_new = inputs, None
        return self.engine.submit(tokens, max_new_tokens=max_new, rid=rid,
                                  priority=priority, timeout_ms=timeout_ms)

    def heartbeat(self):
        if self.engine._dead:
            raise ReplicaLostError(self.replica_id, reason="engine dead")
        return True

    def probe(self):
        """Deepcheck: a real single-token decode through the compiled
        ladder (prefill + step + eviction)."""
        fut = self.engine.submit([1], max_new_tokens=1,
                                 priority="best_effort")
        return fut.result(30.0)

    def swap(self, arg_params=None, aux_params=None, checkpoint_dir=None):
        from ..llm import stack_lm_params
        from .replica import _load_checkpoint_params
        if checkpoint_dir is not None:
            arg_params, _ = _load_checkpoint_params(checkpoint_dir)
        if arg_params is None:
            raise MXNetError("DecodeReplica.swap: no parameter source")
        stacked = stack_lm_params(arg_params, self._cfg)
        # same shapes, same programs: the signature is unchanged so the
        # swap costs zero XLA compiles (params are call arguments)
        self.engine.programs.params = stacked
        self.version += 1
        return self.version

    def outstanding(self):
        return self.engine.outstanding()

    def estimated_wait_s(self):
        return self.engine.estimated_wait_s()

    def stats(self):
        st = self.engine.stats()
        st["version"] = self.version
        return st

    def kill(self):
        self.engine.kill()

    def close(self, drain=True):
        self.engine.close(drain=drain)
