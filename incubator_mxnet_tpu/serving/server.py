"""ModelServer: the multi-model serving front end.

Owns a registry of ``name -> (ServedModel, MicroBatcher, ServingMetrics)``.
Models load from classic checkpoint pairs, elastic ``checkpoint/``
directories, or pre-built `ServedModel`s; every load warms the bucket
ladder by default so steady-state traffic never compiles.  Loading over an
existing name hot-swaps: the new model starts taking requests first, then
the old batcher drains — in-flight requests complete against the weights
they were submitted under, none are dropped.  `shutdown(drain=True)`
drains every model.
"""
from __future__ import annotations

from ..analysis import locks as _locks
from ..base import MXNetError
from .batcher import MicroBatcher
from .metrics import ServingMetrics
from .model import ServedModel, DEFAULT_BUCKETS

__all__ = ["ModelServer"]


class ModelServer:
    """Dynamic-batching inference server over named models."""

    def __init__(self, max_batch_size=None, max_queue_latency_ms=2.0,
                 max_queue=256, ctx=None):
        self._defaults = {"max_batch_size": max_batch_size,
                          "max_queue_latency_ms": max_queue_latency_ms,
                          "max_queue": max_queue}
        self._ctx = ctx
        self._models = {}
        self._lock = _locks.make_lock("serving.server")
        self._closed = False
        # telemetry plane: the per-model snapshots under 'server'
        # (each model's ServingMetrics also self-registers under
        # 'serving.<name>'; this is the whole-server view)
        from ..obs import metrics as _obs_metrics
        _obs_metrics.register_producer("server", self.stats)

    # -- model lifecycle -----------------------------------------------------
    def load_model(self, name, model=None, *, prefix=None, epoch=0,
                   symbol_file=None, checkpoint_dir=None, symbol=None,
                   arg_params=None, aux_params=None, data_shapes=None,
                   buckets=DEFAULT_BUCKETS, warmup=True, **knobs):
        """Register `name`.  Exactly one source: a `ServedModel`, a classic
        ``prefix``/``epoch`` pair, a ``symbol_file`` + ``checkpoint_dir``,
        or an in-memory ``symbol`` + params.  ``knobs`` override the
        server's batching defaults for this model."""
        if self._closed:
            raise MXNetError("serving: server is shut down")
        if model is None:
            common = dict(data_shapes=data_shapes, buckets=buckets,
                          ctx=self._ctx, name=name)
            if prefix is not None:
                model = ServedModel.load(prefix, epoch, **common)
            elif checkpoint_dir is not None:
                if symbol_file is None:
                    raise MXNetError(
                        "serving: checkpoint_dir loading needs symbol_file")
                model = ServedModel.from_checkpoint_dir(
                    symbol_file, checkpoint_dir, **common)
            elif symbol is not None:
                model = ServedModel(symbol, arg_params, aux_params, **common)
            else:
                raise MXNetError(
                    "serving: load_model needs model=, prefix=, "
                    "checkpoint_dir=, or symbol=")
        if warmup and not model.warmed:
            model.warmup()
        cfg = dict(self._defaults)
        cfg.update(knobs)
        metrics = ServingMetrics(name)
        batcher = MicroBatcher(model, metrics, **cfg)
        with self._lock:
            # re-checked under the SAME lock shutdown() empties the dict
            # under: a load racing shutdown must not register a batcher
            # nobody will ever close
            closed = self._closed
            old = None
            if not closed:
                old = self._models.get(name)
                self._models[name] = (model, batcher, metrics)
        if closed:
            batcher.close(drain=False)
            raise MXNetError("serving: server is shut down")
        if old is not None:
            # hot swap: the new batcher is already live; the old one
            # finishes its in-flight work before dying
            old[1].close(drain=True)
        return model

    def unload_model(self, name, drain=True, drain_timeout=None):
        """Remove `name`; with ``drain`` all queued requests complete
        first (none dropped).  ``drain_timeout`` bounds the wait: when a
        wedged request keeps the drain from finishing, the batcher stops
        anyway and a structured `MXNetError` lists the still-pending
        request ids instead of blocking the unload forever."""
        with self._lock:
            entry = self._models.pop(name, None)
        if entry is None:
            raise MXNetError(f"serving: no model named '{name}'")
        entry[1].close(drain=drain, timeout=drain_timeout)

    def models(self):
        with self._lock:
            return sorted(self._models)

    def _entry(self, name):
        with self._lock:
            entry = self._models.get(name)
        if entry is None:
            raise MXNetError(f"serving: no model named '{name}'")
        return entry

    def model(self, name):
        return self._entry(name)[0]

    def batcher(self, name):
        return self._entry(name)[1]

    # -- request path --------------------------------------------------------
    def submit(self, name, inputs, timeout_ms=None, priority=1):
        """Async request: returns a `concurrent.futures.Future` resolving
        to the per-output NDArray list for exactly this request's rows.
        ``priority`` is the dispatch rank (0 first, 2 last; see
        `MicroBatcher.submit`)."""
        return self._entry(name)[1].submit(inputs, timeout_ms=timeout_ms,
                                           priority=priority)

    def predict(self, name, inputs, timeout_ms=None, priority=1):
        """Sync request through the batching path."""
        wait = None if timeout_ms is None else timeout_ms / 1e3 + 60
        return self.submit(name, inputs, timeout_ms=timeout_ms,
                           priority=priority).result(wait)

    # -- observability / lifecycle -------------------------------------------
    def stats(self):
        """{model: metrics snapshot} (see `ServingMetrics.snapshot`)."""
        with self._lock:
            entries = dict(self._models)
        return {name: m.snapshot() for name, (_, _, m) in entries.items()}

    def install_monitor(self, name, mon):
        """Per-layer monitoring on `name`'s request path."""
        self._entry(name)[1].install_monitor(mon)
        return mon

    def shutdown(self, drain=True):
        """Stop every model; with ``drain`` in-flight work completes."""
        with self._lock:
            entries, self._models = dict(self._models), {}
            self._closed = True
        for _, batcher, _m in entries.values():
            batcher.close(drain=drain)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=True)
