"""Serving metrics: QPS, latency percentiles, batch occupancy, queue depth.

One `ServingMetrics` per served model, updated by the micro-batching
scheduler on the hot path (a lock + a few counter increments per batch).
Snapshots are pull-based (`snapshot()` / `ModelServer.stats()`); each
executed batch is also emitted into the profiler's chrome trace when a
profile is running (`profiler.record_serving`), so serving load shows up
in the same trace viewer as the XLA timeline.
"""
from __future__ import annotations

import collections
import threading
import time

import numpy as _np

__all__ = ["ServingMetrics"]


class ServingMetrics:
    """Counters and a sliding latency window for one served model."""

    def __init__(self, model_name, window=4096):
        self.model_name = model_name
        self._lock = threading.Lock()
        self._lat_ms = collections.deque(maxlen=window)
        self._t0 = time.monotonic()
        self.requests = 0        # accepted into the queue
        self.responses = 0       # completed with a result
        self.timeouts = 0        # deadline-exceeded
        self.rejected = 0        # backpressure rejections
        self.batches = 0         # executed device batches
        self.rows = 0            # live request rows executed
        self.capacity = 0        # bucket rows executed (rows + padding)
        self.queue_depth = 0     # gauge, set by the batcher
        # degraded-mode stats (overload controller, resilience layer)
        self.shed = 0            # deadline-unmeetable, rejected pre-queue
        self.breaker_rejects = 0  # failed fast while the breaker was open
        self.breaker_state = "closed"   # gauge, set by the batcher
        self.retries = collections.Counter()   # attempt number -> count
        self._ewma_batch_s = None    # recent batch execution time

    # -- hot-path updates ----------------------------------------------------
    def record_request(self, queue_depth):
        with self._lock:
            self.requests += 1
            self.queue_depth = queue_depth

    def record_batch(self, rows, bucket, dur_s):
        with self._lock:
            self.batches += 1
            self.rows += rows
            self.capacity += bucket
            # EWMA of batch execution time: the overload controller's
            # estimate of how fast the queue drains (shed decisions)
            self._ewma_batch_s = dur_s if self._ewma_batch_s is None \
                else 0.8 * self._ewma_batch_s + 0.2 * dur_s
        from .. import profiler as _profiler
        _profiler.record_serving(f"serving:{self.model_name}",
                                 dur_s * 1e6, rows=rows, bucket=bucket)

    def avg_batch_s(self):
        """Recent batch execution time (EWMA), or None before the first
        executed batch (no shedding until there is an estimate)."""
        with self._lock:
            return self._ewma_batch_s

    def record_shed(self):
        with self._lock:
            self.shed += 1

    def record_breaker_reject(self):
        with self._lock:
            self.breaker_rejects += 1

    def record_retry(self, attempt):
        with self._lock:
            self.retries[int(attempt)] += 1

    def set_breaker_state(self, state):
        with self._lock:
            self.breaker_state = state

    def record_response(self, latency_s):
        with self._lock:
            self.responses += 1
            self._lat_ms.append(latency_s * 1e3)

    def record_timeout(self):
        with self._lock:
            self.timeouts += 1

    def record_reject(self):
        with self._lock:
            self.rejected += 1

    def set_queue_depth(self, depth):
        with self._lock:
            self.queue_depth = depth

    # -- reads ---------------------------------------------------------------
    def snapshot(self):
        """One coherent metrics dict: counts, QPS since start, p50/p99
        latency (ms, over the sliding window), mean batch occupancy."""
        with self._lock:
            lat = _np.asarray(self._lat_ms, dtype=_np.float64)
            elapsed = max(time.monotonic() - self._t0, 1e-9)
            snap = {
                "model": self.model_name,
                "requests": self.requests,
                "responses": self.responses,
                "timeouts": self.timeouts,
                "rejected": self.rejected,
                "batches": self.batches,
                "rows": self.rows,
                "queue_depth": self.queue_depth,
                "qps": self.responses / elapsed,
                "batch_occupancy": (self.rows / self.capacity
                                    if self.capacity else 0.0),
                "avg_batch_rows": (self.rows / self.batches
                                   if self.batches else 0.0),
                "shed": self.shed,
                "breaker_rejects": self.breaker_rejects,
                "breaker_state": self.breaker_state,
                "retry_histogram": dict(self.retries),
                "avg_batch_ms": (self._ewma_batch_s * 1e3
                                 if self._ewma_batch_s is not None else None),
            }
        if lat.size:
            snap["p50_ms"] = float(_np.percentile(lat, 50))
            snap["p99_ms"] = float(_np.percentile(lat, 99))
        else:
            snap["p50_ms"] = snap["p99_ms"] = None
        return snap
