"""Serving metrics: QPS, latency percentiles, batch occupancy, queue depth.

One `ServingMetrics` per served model, updated by the micro-batching
scheduler on the hot path (a lock + a few counter increments per batch).
Snapshots are pull-based (`snapshot()` / `ModelServer.stats()`); each
executed batch is also emitted into the profiler's chrome trace when a
profile is running (`profiler.record_serving`), so serving load shows up
in the same trace viewer as the XLA timeline.
"""
from __future__ import annotations

import collections
import threading
import time

import numpy as _np

__all__ = ["ServingMetrics"]


class ServingMetrics:
    """Counters and a sliding latency window for one served model."""

    def __init__(self, model_name, window=4096):
        self.model_name = model_name
        self._lock = threading.Lock()
        self._lat_ms = collections.deque(maxlen=window)
        self._t0 = time.monotonic()
        self.requests = 0        # accepted into the queue
        self.responses = 0       # completed with a result
        self.timeouts = 0        # deadline-exceeded
        self.rejected = 0        # backpressure rejections
        self.batches = 0         # executed device batches
        self.rows = 0            # live request rows executed
        self.capacity = 0        # bucket rows executed (rows + padding)
        self.queue_depth = 0     # gauge, set by the batcher

    # -- hot-path updates ----------------------------------------------------
    def record_request(self, queue_depth):
        with self._lock:
            self.requests += 1
            self.queue_depth = queue_depth

    def record_batch(self, rows, bucket, dur_s):
        with self._lock:
            self.batches += 1
            self.rows += rows
            self.capacity += bucket
        from .. import profiler as _profiler
        _profiler.record_serving(f"serving:{self.model_name}",
                                 dur_s * 1e6, rows=rows, bucket=bucket)

    def record_response(self, latency_s):
        with self._lock:
            self.responses += 1
            self._lat_ms.append(latency_s * 1e3)

    def record_timeout(self):
        with self._lock:
            self.timeouts += 1

    def record_reject(self):
        with self._lock:
            self.rejected += 1

    def set_queue_depth(self, depth):
        with self._lock:
            self.queue_depth = depth

    # -- reads ---------------------------------------------------------------
    def snapshot(self):
        """One coherent metrics dict: counts, QPS since start, p50/p99
        latency (ms, over the sliding window), mean batch occupancy."""
        with self._lock:
            lat = _np.asarray(self._lat_ms, dtype=_np.float64)
            elapsed = max(time.monotonic() - self._t0, 1e-9)
            snap = {
                "model": self.model_name,
                "requests": self.requests,
                "responses": self.responses,
                "timeouts": self.timeouts,
                "rejected": self.rejected,
                "batches": self.batches,
                "rows": self.rows,
                "queue_depth": self.queue_depth,
                "qps": self.responses / elapsed,
                "batch_occupancy": (self.rows / self.capacity
                                    if self.capacity else 0.0),
                "avg_batch_rows": (self.rows / self.batches
                                   if self.batches else 0.0),
            }
        if lat.size:
            snap["p50_ms"] = float(_np.percentile(lat, 50))
            snap["p99_ms"] = float(_np.percentile(lat, 99))
        else:
            snap["p50_ms"] = snap["p99_ms"] = None
        return snap
