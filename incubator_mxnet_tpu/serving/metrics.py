"""Serving metrics: QPS, latency percentiles, batch occupancy, queue depth.

One `ServingMetrics` per served model, updated by the micro-batching
scheduler on the hot path (a lock + a few counter increments per batch).
Snapshots are pull-based (`snapshot()` / `ModelServer.stats()`); each
executed batch is also emitted into the profiler's chrome trace when a
profile is running (`profiler.record_serving`), so serving load shows up
in the same trace viewer as the XLA timeline.

Latency accounting is a `LatencyReservoir` — a FIXED-size uniform sample
(Vitter's algorithm R) over every response since start, so a week of
traffic costs the same memory as a minute and the percentiles describe
the whole run, not just the last few thousand requests.  Priority-class
traffic (the router's interactive/batch/best-effort split) lands in
per-class shed counters and per-class reservoirs so a degradation claim
("best-effort shed first, interactive p99 inside SLO") is readable off
one snapshot.
"""
from __future__ import annotations

import collections
import random
import time

import numpy as _np

from ..analysis import locks as _locks
from ..analysis import tsan as _tsan
from ..obs import metrics as _obs_metrics

__all__ = ["ServingMetrics", "LatencyReservoir"]


class LatencyReservoir:
    """Bounded uniform sample of a value stream (algorithm R).

    O(1) per record, O(capacity) memory forever: slot i of the first
    `capacity` records is kept verbatim; record n > capacity replaces a
    random slot with probability capacity/n, which keeps the array a
    uniform sample of ALL n records.  The RNG is seeded per reservoir so
    runs are reproducible.  NOT thread-safe on its own — callers hold
    their own metrics lock.
    """

    __slots__ = ("_vals", "count", "_rng", "capacity")

    def __init__(self, capacity=4096, seed=0):
        self.capacity = int(capacity)
        self._vals = _np.empty(self.capacity, dtype=_np.float64)
        self.count = 0
        self._rng = random.Random(seed)

    def add(self, value):
        n = self.count
        if n < self.capacity:
            self._vals[n] = value
        else:
            j = self._rng.randrange(n + 1)
            if j < self.capacity:
                self._vals[j] = value
        self.count = n + 1

    def __len__(self):
        return min(self.count, self.capacity)

    def percentile(self, q):
        """q-th percentile of the sample, or None before any record."""
        n = len(self)
        if not n:
            return None
        return float(_np.percentile(self._vals[:n], q))

    def sample(self):
        return _np.array(self._vals[:len(self)])


class ServingMetrics:
    """Counters and a bounded latency reservoir for one served model."""

    def __init__(self, model_name, window=4096):
        self.model_name = model_name
        self._lock = _locks.make_lock("serving.metrics")
        # every counter write below must hold _lock; under MXNET_TSAN=1
        # an unsynchronized update is attributed to its exact site
        _tsan.instrument(self, f"serving.metrics[{model_name}]")
        # telemetry plane: every per-model metrics instance is a
        # producer under 'serving.<model>' (weakly held — a retired
        # replica's metrics drop out of scrapes with it)
        _obs_metrics.register_producer(f"serving.{model_name}",
                                       self.snapshot)
        self._lat_ms = LatencyReservoir(window)
        self._window = int(window)
        # priority-class plane: class -> {"responses", "shed",
        # "rejected", "lat": LatencyReservoir}; created lazily so
        # single-class (router-less) serving pays nothing
        self._classes = {}
        self._t0 = time.monotonic()
        self.requests = 0        # accepted into the queue
        self.responses = 0       # completed with a result
        self.timeouts = 0        # deadline-exceeded
        self.rejected = 0        # backpressure rejections
        self.batches = 0         # executed device batches
        self.rows = 0            # live request rows executed
        self.capacity = 0        # bucket rows executed (rows + padding)
        self.queue_depth = 0     # gauge, set by the batcher
        # degraded-mode stats (overload controller, resilience layer)
        self.shed = 0            # deadline-unmeetable, rejected pre-queue
        self.breaker_rejects = 0  # failed fast while the breaker was open
        self.breaker_state = "closed"   # gauge, set by the batcher
        self.retries = collections.Counter()   # attempt number -> count
        self._ewma_batch_s = None    # recent batch execution time
        self._ewma_lat_s = None      # recent end-to-end response latency

    # -- hot-path updates ----------------------------------------------------
    def record_request(self, queue_depth):
        with self._lock:
            self.requests += 1
            self.queue_depth = queue_depth

    def record_batch(self, rows, bucket, dur_s):
        with self._lock:
            self.batches += 1
            self.rows += rows
            self.capacity += bucket
            # EWMA of batch execution time: the overload controller's
            # estimate of how fast the queue drains (shed decisions)
            self._ewma_batch_s = dur_s if self._ewma_batch_s is None \
                else 0.8 * self._ewma_batch_s + 0.2 * dur_s
        from .. import profiler as _profiler
        _profiler.record_serving(f"serving:{self.model_name}",
                                 dur_s * 1e6, rows=rows, bucket=bucket)

    def avg_batch_s(self):
        """Recent batch execution time (EWMA), or None before the first
        executed batch (no shedding until there is an estimate)."""
        with self._lock:
            return self._ewma_batch_s

    def _class_locked(self, cls):
        rec = self._classes.get(cls)
        if rec is None:
            # stable per-class seed (str hash is randomized per process)
            import zlib
            rec = self._classes[cls] = {
                "responses": 0, "shed": 0, "rejected": 0,
                "lat": LatencyReservoir(max(self._window // 4, 256),
                                        seed=zlib.crc32(cls.encode()))}
        return rec

    def record_shed(self, cls=None):
        with self._lock:
            self.shed += 1
            if cls is not None:
                self._class_locked(cls)["shed"] += 1

    def record_class_reject(self, cls):
        with self._lock:
            self._class_locked(cls)["rejected"] += 1

    def record_breaker_reject(self):
        with self._lock:
            self.breaker_rejects += 1

    def record_retry(self, attempt):
        with self._lock:
            self.retries[int(attempt)] += 1

    def set_breaker_state(self, state):
        with self._lock:
            self.breaker_state = state

    def record_response(self, latency_s, cls=None):
        with self._lock:
            self.responses += 1
            self._lat_ms.add(latency_s * 1e3)
            self._ewma_lat_s = latency_s if self._ewma_lat_s is None \
                else 0.8 * self._ewma_lat_s + 0.2 * latency_s
            if cls is not None:
                rec = self._class_locked(cls)
                rec["responses"] += 1
                rec["lat"].add(latency_s * 1e3)

    def avg_latency_s(self):
        """Recent end-to-end response latency (EWMA), or None before the
        first response.  Unlike `avg_batch_s` this includes queueing and
        host scheduling — what a NEW request actually experiences — so
        overload estimators should prefer it."""
        with self._lock:
            return self._ewma_lat_s

    def record_timeout(self):
        with self._lock:
            self.timeouts += 1

    def record_reject(self):
        with self._lock:
            self.rejected += 1

    def set_queue_depth(self, depth):
        with self._lock:
            self.queue_depth = depth

    # -- reads ---------------------------------------------------------------
    def snapshot(self):
        """One coherent metrics dict: counts, QPS since start, p50/p99
        latency (ms, reservoir-sampled over the whole run), mean batch
        occupancy, and a per-priority-class block when router traffic
        carried classes."""
        with self._lock:
            lat = self._lat_ms.sample()
            classes = {
                cls: {"responses": rec["responses"],
                      "shed": rec["shed"],
                      "rejected": rec["rejected"],
                      "p50_ms": rec["lat"].percentile(50),
                      "p99_ms": rec["lat"].percentile(99)}
                for cls, rec in self._classes.items()}
            elapsed = max(time.monotonic() - self._t0, 1e-9)
            snap = {
                "model": self.model_name,
                "requests": self.requests,
                "responses": self.responses,
                "timeouts": self.timeouts,
                "rejected": self.rejected,
                "batches": self.batches,
                "rows": self.rows,
                "queue_depth": self.queue_depth,
                "qps": self.responses / elapsed,
                "batch_occupancy": (self.rows / self.capacity
                                    if self.capacity else 0.0),
                "avg_batch_rows": (self.rows / self.batches
                                   if self.batches else 0.0),
                "shed": self.shed,
                "breaker_rejects": self.breaker_rejects,
                "breaker_state": self.breaker_state,
                "retry_histogram": dict(self.retries),
                "avg_batch_ms": (self._ewma_batch_s * 1e3
                                 if self._ewma_batch_s is not None else None),
            }
            if classes:
                snap["classes"] = classes
        if lat.size:
            snap["p50_ms"] = float(_np.percentile(lat, 50))
            snap["p99_ms"] = float(_np.percentile(lat, 99))
        else:
            snap["p50_ms"] = snap["p99_ms"] = None
        return snap
