"""ReplicaRouter: health-checked request routing over N model replicas.

The availability layer of the serving plane.  One process dying — or
one bad weight reload — must cost capacity, not the model: the unit of
redundancy is the REPLICA (the parameter-server failover model of the
MXNet paper, the replica-fleet production story of the TensorFlow
paper), and this router composes the repo's existing ingredients
around it:

* **least-loaded, health- and breaker-aware dispatch** — each request
  goes to the live replica with the least outstanding work; a replica
  whose requests keep failing trips its `CircuitBreaker` and is skipped
  while it cools off.
* **liveness** — a health thread heartbeats every replica on an
  interval, with every k-th beat a *deepcheck* (a real bucket-1
  inference through the compiled ladder).  The judgement is
  `dist.membership` semantics: a failed probe makes a replica
  *suspect* (dispreferred for new work, never evicted — even a
  correlated probe-drop burst across the whole fleet only reorders
  preference); only probe silence older than the deadline makes it
  *dead*, and a completed request counts as proof of life.
* **failover, idempotent by request id** — when a replica dies with
  requests in flight, each unresolved request is re-dispatched to a
  survivor.  A request is re-dispatched ONLY on `ReplicaLostError`
  (replica death), never on a caller error; the first result to arrive
  wins the future, late duplicates are counted and dropped, and remote
  workers deduplicate by rid so a transport resend can never execute
  twice on one worker.
* **hot weight-swap, replica by replica** — `swap_weights()` rolls a
  new parameter set (typically the newest valid elastic checkpoint)
  through the fleet: each replica in turn stops taking new work, drains
  its in-flight requests, swaps in place (same shapes, same programs —
  zero XLA compiles), passes a deepcheck, and rejoins.  The rest of
  the fleet keeps serving, so no request is dropped, and every request
  is served wholly by one replica at one version (never mixed).  A
  failed swap aborts the roll with the fleet still serving.
* **priority classes** — requests carry ``priority`` in
  {"interactive", "batch", "best_effort"}.  Under overload (estimated
  queue wait beyond the class's shed threshold) low classes shed
  FIRST, so an N-1 fleet keeps interactive p99 inside SLO by shedding
  best-effort traffic; per-class latency/shed counters make the
  degradation visible in `stats()`.

Fault sites (`resilience.faults`): ``router.dispatch`` (per dispatch,
names replica + rid), ``replica.health`` (per probe), ``replica.swap``
(per replica swap step).
"""
from __future__ import annotations

import threading
import time

from concurrent.futures import Future

from ..analysis import locks as _locks
from ..analysis import tsan as _tsan
from ..base import MXNetError
from ..obs import metrics as _obs_metrics
from ..obs import trace as _obs_trace
from ..resilience import CircuitBreaker, faults as _faults
from .metrics import ServingMetrics
from .replica import ReplicaLostError

__all__ = ["ReplicaRouter", "SwapInProgressError", "PRIORITIES"]

PRIORITIES = ("interactive", "batch", "best_effort")
# dispatch rank inside replica queues: interactive is served first even
# when lower classes were admitted ahead of it
PRIORITY_RANK = {"interactive": 0, "batch": 1, "best_effort": 2}

HEALTHY, SUSPECT, SWAPPING, DEAD = "healthy", "suspect", "swapping", "dead"


class SwapInProgressError(MXNetError):
    """A weight swap is already rolling through this fleet.

    Carries ``version`` — whatever label the in-flight swap was issued
    under (the registry version for loop-driven swaps, the checkpoint
    dir or ``"<params>"`` otherwise) — so a watcher like the
    LoopController can log WHAT it is waiting behind and back off to its
    next poll instead of treating the collision as a failed canary.
    """

    def __init__(self, router, version):
        self.router = router
        self.version = version
        super().__init__(
            f"router '{router}': a weight swap is already in progress "
            f"(in-flight: {version!r})")


class _Slot:
    """Router-side bookkeeping for one replica."""

    def __init__(self, replica, breaker, now):
        self.replica = replica
        self.state = HEALTHY
        self.breaker = breaker
        self.last_ok = now
        self.probe_failures = 0    # consecutive
        self.probes = 0
        self.deepchecks = 0
        self.dispatching = 0       # submits claimed but not yet handed
                                   # to the replica (the swap fence)


class _RouterRequest:
    __slots__ = ("rid", "inputs", "timeout_ms", "priority", "future",
                 "dispatches", "replica_id", "t0", "lock", "done", "span")

    def __init__(self, rid, inputs, timeout_ms, priority, now):
        self.rid = rid
        self.inputs = inputs
        self.timeout_ms = timeout_ms
        self.priority = priority
        self.future = Future()
        self.future.request_id = rid
        self.dispatches = 0
        self.replica_id = None
        self.t0 = now
        self.lock = _locks.make_lock("serving.router.request")
        self.done = False
        # the request's trace root: dispatch attempts and the remote
        # worker's execute span parent into it (ends at _resolve)
        self.span = _obs_trace.start_span("router.request", cat="serving",
                                          rid=rid, priority=priority)


class ReplicaRouter:
    """Front-end router over `Replica` handles (see module docstring)."""

    def __init__(self, replicas=(), name="router", health_interval_s=None,
                 health_deadline_s=None, deepcheck_every=None,
                 max_dispatches=None, shed_ms=None, clock=time.monotonic):
        from .. import config as _config
        self.name = str(name)
        self._clock = clock
        self.health_interval_s = float(
            health_interval_s if health_interval_s is not None
            else _config.get("MXNET_ROUTER_HEALTH_INTERVAL_S"))
        self.health_deadline_s = float(
            health_deadline_s if health_deadline_s is not None
            else _config.get("MXNET_ROUTER_HEALTH_DEADLINE_S"))
        self.deepcheck_every = int(
            deepcheck_every if deepcheck_every is not None
            else _config.get("MXNET_ROUTER_DEEPCHECK_EVERY"))
        self.max_dispatches = int(
            max_dispatches if max_dispatches is not None
            else _config.get("MXNET_ROUTER_MAX_DISPATCHES"))
        self.shed_ms = dict(shed_ms) if shed_ms is not None else {
            "best_effort": float(
                _config.get("MXNET_ROUTER_SHED_BEST_EFFORT_MS")),
            "batch": float(_config.get("MXNET_ROUTER_SHED_BATCH_MS")),
            "interactive": float(
                _config.get("MXNET_ROUTER_SHED_INTERACTIVE_MS"))}
        self.metrics = ServingMetrics(self.name)
        # telemetry plane: this router's stats() under the stable
        # 'router' namespace (dotted suffix for non-default names)
        _obs_metrics.register_producer(
            "router" if self.name == "router" else f"router.{self.name}",
            self.stats)
        self._lock = _locks.make_lock("serving.router")
        self._slots = {}               # replica_id -> _Slot
        self._inflight = {}            # rid -> _RouterRequest
        # resolved rids, insertion-ordered so the bounded trim drops the
        # OLDEST first (the idempotency window must keep recent ids)
        self._completed = {}           # rid -> True
        self._completed_cap = 65536
        self._rid_counter = 0
        # generated ids live in their own namespace so they can never
        # collide with a caller-supplied request_id
        import uuid
        self._rid_ns = uuid.uuid4().hex[:8]
        self._swap_lock = _locks.make_lock("serving.router.swap")
        self._swap_inflight = None   # label of the swap holding the lock
        self._closed = threading.Event()
        _tsan.instrument(self, f"serving.router[{self.name}]")
        # fleet counters
        self.failovers = 0
        self.duplicates_suppressed = 0
        self.replicas_lost = 0
        self.swaps_committed = 0
        for r in replicas:
            self.add_replica(r)
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True,
            name=f"mx-router-{self.name}-health")
        self._health_thread.start()

    # -- fleet membership -----------------------------------------------------
    def add_replica(self, replica):
        from .. import config as _config
        breaker = CircuitBreaker(
            failure_threshold=int(
                _config.get("MXNET_SERVING_BREAKER_THRESHOLD")),
            reset_timeout=float(
                _config.get("MXNET_SERVING_BREAKER_RESET_S")))
        slot = _tsan.instrument(
            _Slot(replica, breaker, self._clock()),
            f"serving.router.slot[{replica.replica_id}]")
        with self._lock:
            if replica.replica_id in self._slots:
                raise MXNetError(
                    f"router '{self.name}': duplicate replica id "
                    f"{replica.replica_id!r}")
            self._slots[replica.replica_id] = slot
        return replica

    def remove_replica(self, replica_id, drain=True):
        with self._lock:
            slot = self._slots.pop(replica_id, None)
        if slot is None:
            raise MXNetError(f"router '{self.name}': no replica "
                             f"{replica_id!r}")
        slot.replica.close(drain=drain)

    def replicas(self):
        with self._lock:
            return sorted(self._slots)

    def replica(self, replica_id):
        """The live `Replica` handle for `replica_id` — the loop
        controller scores its canary through this, on the same
        submit path real traffic uses."""
        with self._lock:
            slot = self._slots.get(replica_id)
            if slot is None or slot.state == DEAD:
                raise MXNetError(f"router '{self.name}': no live replica "
                                 f"{replica_id!r}")
            return slot.replica

    # -- dispatch -------------------------------------------------------------
    def _eligible_locked(self):
        # state-only filter: checking `breaker.state` (unlike `allow()`)
        # consumes no half-open probe token, so load estimation never
        # wedges a breaker.  SUSPECT replicas count: they are still
        # serving, just not first choice.
        return [s for s in self._slots.values()
                if s.state in (HEALTHY, SUSPECT)
                and s.breaker.state != "open"]

    def _pick(self, exclude=()):
        """Least-loaded live replica (breaker-aware), or None.  Healthy
        replicas are preferred; suspect ones (a failed probe inside the
        liveness deadline) are the fallback tier — a correlated
        probe-drop burst must degrade PREFERENCE, never availability.
        Only the chosen slot's `allow()` is consulted — it may consume
        that breaker's half-open probe token, which the dispatch
        outcome then settles (success/failure/release)."""
        with self._lock:
            cands = [s for s in self._eligible_locked()
                     if s.replica.replica_id not in exclude]
        cands.sort(key=lambda s: (s.state != HEALTHY,
                                  s.replica.outstanding()))
        for s in cands:
            if s.breaker.allow():
                return s
        return None

    def _fleet_wait_s(self):
        """The wait a new request faces: the BEST estimated wait among
        live replicas (that is the queue the request would join)."""
        with self._lock:
            slots = self._eligible_locked()
        waits = [w for s in slots
                 if (w := s.replica.estimated_wait_s()) is not None]
        if not waits or len(waits) < len(slots):
            # any replica without an estimate is assumed free
            return 0.0 if slots else None
        return min(waits)

    def estimated_wait_s(self):
        """The queue-model wait a NEW request faces on this fleet — the
        same signal the admission controller sheds on.  The fleet
        autoscaler (`serving.fleet.FleetManager`) reads this every tick
        so scaling and shedding act on one number, never two estimates
        that can disagree.  None when no replica is live."""
        return self._fleet_wait_s()

    def submit(self, inputs, timeout_ms=None, priority="interactive",
               request_id=None):
        """Route one request; returns a Future resolving to the
        per-output array list.  ``priority`` picks the shed class;
        ``request_id`` (optional) is the idempotency key — re-submitting
        an id the router already completed is rejected."""
        if self._closed.is_set():
            raise MXNetError(f"router '{self.name}' is shut down")
        if priority not in PRIORITIES:
            raise MXNetError(
                f"router '{self.name}': unknown priority {priority!r} "
                f"(one of {', '.join(PRIORITIES)})")
        # graceful degradation: shed the low classes FIRST when the
        # fleet cannot keep up — interactive traffic rides out an N-1
        # fleet because best-effort work was refused admission
        wait = self._fleet_wait_s()
        if wait is not None and wait * 1e3 > self.shed_ms[priority]:
            self.metrics.record_shed(priority)
            raise MXNetError(
                f"router '{self.name}': overloaded — estimated fleet "
                f"wait {wait * 1e3:.0f} ms exceeds the {priority} "
                f"class's {self.shed_ms[priority]:g} ms shed threshold")
        with self._lock:
            self._rid_counter += 1
            rid = request_id if request_id is not None \
                else f"{self.name}/{self._rid_ns}-{self._rid_counter}"
            if rid in self._completed or rid in self._inflight:
                raise MXNetError(
                    f"router '{self.name}': request id {rid!r} was "
                    "already accepted (idempotency: it will not execute "
                    "twice)")
            req = _RouterRequest(rid, inputs, timeout_ms, priority,
                                 self._clock())
            self._inflight[rid] = req
        self.metrics.record_request(len(self._inflight))
        try:
            self._dispatch(req)
        except BaseException:
            # ANY dispatch failure (including non-MXNetError injected
            # faults) must release the rid, or _inflight leaks and a
            # caller's retry of the same request_id is refused forever
            with self._lock:
                self._inflight.pop(rid, None)
            req.span.end(outcome="rejected")
            raise
        return req.future

    def predict(self, inputs, timeout_ms=None, priority="interactive",
                request_id=None):
        wait = None if timeout_ms is None else timeout_ms / 1e3 + 60
        return self.submit(inputs, timeout_ms=timeout_ms, priority=priority,
                           request_id=request_id).result(wait)

    def _dispatch(self, req, exclude=()):
        while True:
            slot = self._pick(exclude=exclude)
            if slot is None:
                with self._lock:
                    states = {s.replica.replica_id: s.state
                              for s in self._slots.values()}
                raise MXNetError(
                    f"router '{self.name}': no live replica to dispatch "
                    f"to (fleet: {states or 'empty'})")
            with self._lock:
                if slot.state not in (HEALTHY, SUSPECT):
                    # state flipped (swap/eviction) between pick and
                    # claim: hand the probe token back and re-pick
                    slot.breaker.release_probe()
                    continue
                # the swap fence: swap_weights waits for dispatching==0
                # AFTER going SWAPPING, so no request claimed here can
                # start executing while parameters are being replaced
                slot.dispatching += 1
            break
        req.dispatches += 1
        req.replica_id = slot.replica.replica_id
        try:
            _faults.fire("router.dispatch", replica=req.replica_id,
                         rid=req.rid, attempt=req.dispatches)
            try:
                # trace context: the replica's submit path (batcher
                # enqueue / transport frame) parents into this request
                with _obs_trace.activate(req.span):
                    inner = slot.replica.submit(req.inputs,
                                                timeout_ms=req.timeout_ms,
                                                rid=req.rid,
                                                priority=PRIORITY_RANK[
                                                    req.priority])
            except ReplicaLostError:
                self._on_replica_lost(slot)
                return self._failover(req, exclude + (req.replica_id,))
            except MXNetError:
                # caller/backpressure error from a live replica: it
                # would fail identically anywhere — surface it, no
                # failover (but hand back the half-open probe token
                # `allow()` may have consumed: nothing executed to
                # settle it)
                slot.breaker.release_probe()
                self.metrics.record_class_reject(req.priority)
                raise
        finally:
            with self._lock:
                slot.dispatching -= 1
        inner.add_done_callback(
            lambda fut, req=req, slot=slot: self._on_done(req, slot, fut))

    def _failover(self, req, exclude):
        if req.dispatches >= self.max_dispatches:
            self._resolve(req, error=MXNetError(
                f"router '{self.name}': request {req.rid} failed on "
                f"{req.dispatches} replica(s) "
                f"({', '.join(exclude)}) — dispatch budget exhausted"))
            return
        with self._lock:
            self.failovers += 1
        _faults.note("failover", site="router.dispatch", rid=req.rid,
                     attempt=req.dispatches + 1)
        try:
            self._dispatch(req, exclude=exclude)
        except MXNetError as exc:
            self._resolve(req, error=exc)

    def _on_done(self, req, slot, inner):
        """Completion callback for one dispatch attempt."""
        try:
            result = inner.result()
            err = None
        except Exception as exc:   # noqa: BLE001 — classified below
            result, err = None, exc
        if err is None:
            slot.breaker.record_success()
            with self._lock:
                # proof of life: a served request refreshes liveness.
                # Written under the router lock — the health thread
                # updates the same field (mxtsan: shared-state-race)
                slot.last_ok = self._clock()
            self._resolve(req, result=result)
            return
        if isinstance(err, ReplicaLostError):
            # replica death with this request unresolved: fail over —
            # a dead replica cannot be executing it anymore, and the
            # completed-rid check keeps an already-answered request
            # from running again
            self._on_replica_lost(slot)
            with req.lock:
                already = req.done
            if not already:
                self._failover(req, (req.replica_id or "",))
            return
        slot.breaker.record_failure()
        self._resolve(req, error=err)

    def _resolve(self, req, result=None, error=None):
        """Complete the router future exactly once; late duplicates
        (a replica wrongly presumed dead answering after failover) are
        counted and dropped — the caller can never observe two
        results."""
        with req.lock:
            if req.done:
                with self._lock:
                    self.duplicates_suppressed += 1
                return
            req.done = True
        with self._lock:
            self._inflight.pop(req.rid, None)
            self._completed[req.rid] = True
            while len(self._completed) > self._completed_cap:
                # bounded, oldest-first: idempotency only needs to
                # cover the failover horizon, which is recent by nature
                self._completed.pop(next(iter(self._completed)))
        req.span.end(outcome="error" if error is not None else "ok")
        try:
            if error is not None:
                req.future.set_exception(error)
            else:
                req.future.set_result(result)
                self.metrics.record_response(
                    self._clock() - req.t0, cls=req.priority)
        except Exception:
            pass   # caller cancelled it meanwhile

    # -- health ---------------------------------------------------------------
    def declare_lost(self, replica_id):
        """Externally declare one replica dead (the fleet layer's
        host-loss path: a dead HOST kills every replica placed on it at
        once, without waiting for each replica's own probe silence to
        cross the liveness deadline).  In-flight requests fail over
        immediately; unknown ids are ignored (the replica may already
        have been removed)."""
        with self._lock:
            slot = self._slots.get(replica_id)
        if slot is not None:
            self._on_replica_lost(slot)

    def _on_replica_lost(self, slot):
        with self._lock:
            if slot.state == DEAD:
                return
            slot.state = DEAD
            self.replicas_lost += 1
        _faults.note("replica_lost", site="replica.health",
                     replica=slot.replica.replica_id)
        # fail everything it still holds so the failover callbacks fire
        # now instead of at the transport timeout
        mark = getattr(slot.replica, "_mark_lost", None)
        if mark is not None:
            mark("router declared the replica dead")

    def _health_loop(self):
        # slot bookkeeping (probes, state, last_ok) is written under the
        # router lock — the dispatch path and `_on_done` write the same
        # fields from other threads (mxtsan flagged the lock-free
        # version as shared-state races).  The probe's network call
        # itself runs OUTSIDE the lock: a slow replica must not block
        # dispatch, and a blocking call under a contended lock is
        # exactly what the sanitizer's blocking pass exists to catch.
        while not self._closed.wait(self.health_interval_s):
            with self._lock:
                slots = list(self._slots.values())
            for slot in slots:
                with self._lock:
                    if slot.state in (DEAD, SWAPPING):
                        continue
                    slot.probes += 1
                    deep = self.deepcheck_every > 0 and \
                        slot.probes % self.deepcheck_every == 0
                    if deep:
                        slot.deepchecks += 1
                try:
                    _faults.fire("replica.health",
                                 replica=slot.replica.replica_id,
                                 deep=deep)
                    if deep:
                        slot.replica.probe()
                    else:
                        slot.replica.heartbeat()
                    with self._lock:
                        slot.last_ok = self._clock()
                        slot.probe_failures = 0
                        if slot.state == SUSPECT:
                            slot.state = HEALTHY
                except ReplicaLostError:
                    self._on_replica_lost(slot)
                except Exception:
                    # a dropped/failed probe alone NEVER evicts: the
                    # replica goes suspect (no new work) until either a
                    # probe lands (healthy) or silence crosses the
                    # deadline (dead).  Served requests also refresh
                    # last_ok — a replica busy serving is alive even
                    # when its probes are being dropped.
                    with self._lock:
                        slot.probe_failures += 1
                        if slot.state == HEALTHY:
                            slot.state = SUSPECT
                with self._lock:
                    overdue = slot.state != DEAD and \
                        self._clock() - slot.last_ok > \
                        self.health_deadline_s
                if overdue:
                    self._on_replica_lost(slot)

    # -- hot weight swap ------------------------------------------------------
    def _acquire_swap(self, version):
        """Take the fleet-wide swap lock (non-blocking) and record what
        is rolling, so a collision can name the in-flight swap."""
        if not self._swap_lock.acquire(blocking=False):
            with self._lock:
                inflight = self._swap_inflight
            raise SwapInProgressError(self.name, inflight)
        with self._lock:
            self._swap_inflight = version

    def _release_swap(self):
        with self._lock:
            self._swap_inflight = None
        self._swap_lock.release()

    def _swap_slot(self, slot, arg_params, aux_params, checkpoint_dir,
                   drain_timeout_s):
        """Drain + swap + deepcheck ONE slot (caller holds the swap
        lock).  Returns None on success, else the failure exception —
        with the slot's state already restored (or the slot declared
        lost on `ReplicaLostError`)."""
        replica = slot.replica
        with self._lock:
            if slot.state == DEAD:
                return ReplicaLostError(replica.replica_id, None,
                                        "replica died before its swap")
            slot.state = SWAPPING
        try:
            deadline = self._clock() + float(drain_timeout_s)
            # drain BOTH the replica's queue and any dispatch
            # already claimed before the state flipped to
            # SWAPPING (the fence `_dispatch` increments under
            # the lock) — nothing may start executing while
            # parameters are being replaced
            while (replica.outstanding() or slot.dispatching) \
                    and self._clock() < deadline:
                time.sleep(0.002)
            if replica.outstanding() or slot.dispatching:
                raise MXNetError(
                    f"replica '{replica.replica_id}' did not "
                    f"drain within {drain_timeout_s:g}s")
            _faults.fire("replica.swap",
                         replica=replica.replica_id,
                         version=replica.version + 1)
            replica.swap(arg_params=arg_params,
                         aux_params=aux_params,
                         checkpoint_dir=checkpoint_dir)
            replica.probe()   # deepcheck before rejoining
        except ReplicaLostError as exc:
            self._on_replica_lost(slot)
            return exc
        except Exception as exc:
            with self._lock:
                if slot.state == SWAPPING:
                    slot.state = HEALTHY
            return exc
        with self._lock:
            if slot.state == SWAPPING:
                slot.state = HEALTHY
            slot.last_ok = self._clock()
        return None

    def swap_weights(self, checkpoint_dir=None, arg_params=None,
                     aux_params=None, drain_timeout_s=60.0, version=None):
        """Roll new weights through the fleet, one replica at a time.

        Each replica: out of rotation -> drain in-flight -> swap (zero
        XLA compiles: same shapes, same programs) -> deepcheck -> back
        in rotation.  The remaining fleet serves throughout, so zero
        requests are dropped; each request is served entirely at one
        weight version.  On any failure the roll ABORTS with a
        structured error naming swapped vs unswapped replicas — the
        fleet keeps serving (briefly mixed-version across REPLICAS,
        never within a request); re-issue to finish the roll.

        ``version`` is an optional label for this roll (the registry
        version when the loop controller drives it); a concurrent swap
        attempt fails with `SwapInProgressError` naming it.
        """
        self._acquire_swap(version if version is not None
                           else (checkpoint_dir or "<params>"))
        try:
            with self._lock:
                order = [s for s in self._slots.values() if s.state != DEAD]
            swapped, failed = [], None
            for slot in order:
                exc = self._swap_slot(slot, arg_params, aux_params,
                                      checkpoint_dir, drain_timeout_s)
                if exc is not None:
                    failed = (slot.replica.replica_id, exc)
                    break
                swapped.append(slot.replica.replica_id)
            if failed is not None:
                rid, exc = failed
                remaining = [s.replica.replica_id for s in order
                             if s.replica.replica_id not in swapped
                             and s.replica.replica_id != rid]
                done_s = ", ".join(swapped) or "none"
                left_s = ", ".join(remaining) or "none"
                raise MXNetError(
                    f"router '{self.name}': weight swap ABORTED at "
                    f"replica '{rid}': {exc} — swapped [{done_s}], "
                    f"untouched [{left_s}]; the fleet keeps serving "
                    "(each request single-version); fix the source and "
                    "re-issue swap_weights") from exc
            with self._lock:
                self.swaps_committed += 1
            return {"swapped": swapped,
                    "versions": {s.replica.replica_id: s.replica.version
                                 for s in order}}
        finally:
            self._release_swap()

    def swap_one(self, replica_id=None, checkpoint_dir=None,
                 arg_params=None, aux_params=None, drain_timeout_s=60.0,
                 version=None):
        """Swap exactly ONE replica — the canary leg of the loop gate.

        Same drain/swap/deepcheck discipline as `swap_weights`, scoped
        to a single replica (`replica_id`, or the first live one); the
        rest of the fleet serves the incumbent throughout.  Holds the
        same fleet-wide swap lock, so a canary and a rolling swap can
        never interleave; a collision raises `SwapInProgressError`.
        """
        self._acquire_swap(version if version is not None
                           else (checkpoint_dir or "<params>"))
        try:
            with self._lock:
                if replica_id is not None:
                    slot = self._slots.get(replica_id)
                    if slot is None or slot.state == DEAD:
                        raise MXNetError(
                            f"router '{self.name}': no live replica "
                            f"{replica_id!r} to swap")
                else:
                    slot = next((s for s in self._slots.values()
                                 if s.state == HEALTHY), None)
                    if slot is None:
                        raise MXNetError(
                            f"router '{self.name}': no healthy replica "
                            "to swap")
            exc = self._swap_slot(slot, arg_params, aux_params,
                                  checkpoint_dir, drain_timeout_s)
            if exc is not None:
                raise MXNetError(
                    f"router '{self.name}': swap of replica "
                    f"'{slot.replica.replica_id}' failed: {exc} — the "
                    "rest of the fleet keeps serving the incumbent") \
                    from exc
            return {"swapped": [slot.replica.replica_id],
                    "version": slot.replica.version}
        finally:
            self._release_swap()

    # -- observability / lifecycle -------------------------------------------
    def stats(self):
        """Router snapshot: fleet counters, per-class latency/shed, and
        per-replica state."""
        with self._lock:
            slots = dict(self._slots)
            snap = {
                "router": self.name,
                "failovers": self.failovers,
                "duplicates_suppressed": self.duplicates_suppressed,
                "replicas_lost": self.replicas_lost,
                "swaps_committed": self.swaps_committed,
                "inflight": len(self._inflight),
            }
        snap.update(self.metrics.snapshot())
        snap["replicas"] = {
            rid: {"state": s.state,
                  "outstanding": (0 if s.state == DEAD
                                  else s.replica.outstanding()),
                  "version": s.replica.version,
                  "breaker": s.breaker.state,
                  "probes": s.probes,
                  "deepchecks": s.deepchecks,
                  "probe_failures": s.probe_failures,
                  "age_s": round(self._clock() - s.last_ok, 3)}
            for rid, s in slots.items()}
        return snap

    def shutdown(self, drain=True):
        self._closed.set()
        _tsan.join_thread(self._health_thread, 10,
                          owner=f"ReplicaRouter[{self.name}]")
        with self._lock:
            slots, self._slots = dict(self._slots), {}
        for slot in slots.values():
            try:
                slot.replica.close(drain=drain)
            except MXNetError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=True)
