"""ServedModel: a loaded model with a fixed set of compiled shape buckets.

The inference analogue of `fused.FusedTrainStep`: the whole Symbol is one
XLA program per input signature (`fused.FusedInference`), parameters are
device-resident constants, and the signatures are restricted to a FIXED
bucket ladder so a production server pays every compile at `warmup()` and
none afterwards — on TPU a novel request shape otherwise stalls the whole
request stream behind a multi-second XLA compile.

Requests that don't fill a bucket are padded up to the nearest one by
replicating the final row (row-independent inference makes the pad rows
garbage that the caller never sees: every read path slices them off).
Both request paths share the one program cache:

* `infer()` — the synchronous single-request path (the C-predict ABI and
  `tools` drivers route here), and
* the micro-batching scheduler (`serving.batcher`) — coalesces concurrent
  requests into bucket-sized device batches.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from ..context import current_context
from ..ndarray.ndarray import NDArray

__all__ = ["ServedModel", "DEFAULT_BUCKETS"]

DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32)


def _as_desc_list(data_shapes):
    """Normalize [(name, shape)] / [DataDesc] -> [(name, tuple(shape))]."""
    out = []
    for d in data_shapes:
        name, shape = (d.name, d.shape) if hasattr(d, "name") else \
            (d[0], d[1])
        out.append((str(name), tuple(int(s) for s in shape)))
    return out


class ServedModel:
    """One model compiled over a bucket ladder, ready to serve.

    Parameters
    ----------
    symbol : Symbol
        The inference graph.
    arg_params / aux_params : dict
        Parameter values (NDArray or numpy).  Arguments the dicts omit
        (e.g. a loss head's label input) are bound to zeros, matching the
        `simple_bind` convention the C-predict ABI relies on.
    data_shapes : list of (name, shape) or DataDesc
        The request inputs.  ``shape[0]`` is the batch axis and is
        replaced by each bucket size; the remaining dims are fixed.
    buckets : tuple of int
        Batch-size ladder, compiled at `warmup()`.  ``max(buckets)`` is
        the server's `max_batch_size` for this model.
    """

    def __init__(self, symbol, arg_params, aux_params=None, data_shapes=None,
                 buckets=DEFAULT_BUCKETS, ctx=None, name="model",
                 dtype=_np.float32):
        if not data_shapes:
            raise MXNetError(f"ServedModel('{name}'): data_shapes required")
        self.name = str(name)
        self._ctx = ctx if ctx is not None else current_context()
        self._symbol = symbol
        self.buckets = tuple(sorted({int(b) for b in buckets}))
        if not self.buckets or self.buckets[0] < 1:
            raise MXNetError(f"ServedModel('{name}'): buckets must be "
                             "positive ints")
        descs = _as_desc_list(data_shapes)
        self.data_names = [n for n, _ in descs]
        self._declared_shapes = dict(descs)      # full, as given (C ABI)
        self._sample_shapes = {n: s[1:] for n, s in descs}
        self._dtype = _np.dtype(dtype)
        self.output_names = symbol.list_outputs()

        from .. import fused as _fused
        self._infer = _fused.FusedInference(symbol, self._ctx,
                                            self.data_names,
                                            audit_key=f"serving/{self.name}")
        self._extra_cache = {}   # input-shape key -> zero extras list
        self.set_params(arg_params, aux_params)
        self._monitor = None
        self.warmed = False

    # -- loading -------------------------------------------------------------
    @classmethod
    def load(cls, prefix, epoch=0, **kwargs):
        """From the classic checkpoint pair ``prefix-symbol.json`` +
        ``prefix-%04d.params`` (`model.load_checkpoint`)."""
        from ..model import load_checkpoint
        sym, args, auxs = load_checkpoint(prefix, epoch)
        return cls(sym, args, auxs, **kwargs)

    @classmethod
    def from_checkpoint_dir(cls, symbol_file, checkpoint_path, **kwargs):
        """From a symbol JSON file plus an elastic `checkpoint/` directory
        (or a root of them — the newest VALID one is used; torn
        checkpoints are never selected)."""
        import os
        from .. import symbol as _sym
        from ..checkpoint import load as _load, latest as _latest
        from ..checkpoint.state import split_params
        sym = _sym.load(symbol_file)
        path = checkpoint_path
        if not os.path.exists(os.path.join(path, "manifest.json")):
            found = _latest(path)
            if found is None:
                raise MXNetError(
                    f"ServedModel: no valid checkpoint under {path!r}")
            path = found
        # a training run's programs/ payload (compile/ subsystem): the
        # serialized executables its fused graphs compiled — and, when a
        # server exported its own warmup, the bucket ladder too — load
        # from disk here instead of recompiling at warmup
        from .. import compile as _compile
        for root in (checkpoint_path, os.path.dirname(path)):
            _compile.add_source(os.path.join(root, "programs"))
        data = _load(path)
        args, auxs = split_params(data.arrays)
        return cls(sym, args, auxs, **kwargs)

    # -- buckets -------------------------------------------------------------
    @property
    def max_batch_size(self):
        return self.buckets[-1]

    def bucket_for(self, n):
        """Smallest bucket >= n, or None when n exceeds the ladder."""
        for b in self.buckets:
            if n <= b:
                return b
        return None

    def _input_shapes(self, bucket):
        return {n: (bucket,) + self._sample_shapes[n]
                for n in self.data_names}

    def _extras(self, input_shapes):
        """Zeros for argument slots the param dict left unfilled (a loss
        head's labels), shaped by inference at these input shapes — their
        shapes may follow the batch axis, so each bucket gets its own."""
        key = tuple(sorted(input_shapes.items()))
        got = self._extra_cache.get(key)
        if got is None:
            names = self._infer.extra_names
            if not names:
                got = ()
            else:
                arg_shapes, _, _ = self._symbol.infer_shape(**input_shapes)
                by_name = dict(zip(self._symbol.list_arguments(),
                                   arg_shapes))
                got = tuple(_np.zeros(by_name[n], _np.float32)
                            for n in names)
            self._extra_cache[key] = got
        return got

    # -- execution -----------------------------------------------------------
    def warmup(self):
        """Compile every bucket up front.  Each bucket's signature is
        REGISTERED with the recompile auditor before compiling, so the
        warmup compiles never read as shape churn — after this, any new
        signature the auditor sees is a real post-warmup recompile."""
        for b in self.buckets:
            inputs = [_np.zeros((b,) + self._sample_shapes[n], self._dtype)
                      for n in self.data_names]
            self._infer.register_warm(inputs)
            self.run_bucket(inputs, b)
        self.warmed = True

    def run_bucket(self, arrs, bucket):
        """Dispatch one bucket-shaped batch (already padded) through the
        shared program cache."""
        return self._run(arrs, self._extras(self._input_shapes(bucket)))

    def _run(self, inputs, extras):
        """Low-level dispatch; fires the monitor callback over the
        batched outputs."""
        outs = self._infer(inputs, extras)
        mon = self._monitor
        if mon is not None:
            for out_name, arr in zip(self.output_names, outs):
                mon(out_name, NDArray(arr, ctx=self._ctx))
        return outs

    def prepare_rows(self, inputs):
        """Normalize a request's inputs to ``(rows, [np arrays])`` in
        `data_names` order.  Accepts a dict or a positional list; a bare
        sample (ndim == sample ndim) is promoted to a batch of one.  All
        inputs must agree on the batch axis."""
        if isinstance(inputs, dict):
            missing = [n for n in self.data_names if n not in inputs]
            if missing:
                raise MXNetError(f"serving: model '{self.name}' request "
                                 f"missing inputs {missing}")
            vals = [inputs[n] for n in self.data_names]
        else:
            vals = list(inputs)
            if len(vals) != len(self.data_names):
                raise MXNetError(
                    f"serving: model '{self.name}' expects "
                    f"{len(self.data_names)} inputs, got {len(vals)}")
        rows = None
        arrs = []
        for name, v in zip(self.data_names, vals):
            # requests are host-normalized for coalescing/concat; an
            # NDArray input is read once here by design
            a = (v.asnumpy()  # mxlint: disable=host-sync-in-loop
                 if isinstance(v, NDArray) else _np.asarray(v))
            sample = self._sample_shapes[name]
            if a.ndim == len(sample):
                a = a[None]
            if tuple(a.shape[1:]) != sample:
                raise MXNetError(
                    f"serving: model '{self.name}' input '{name}' has "
                    f"sample shape {tuple(a.shape[1:])}, expected {sample}")
            if a.dtype != self._dtype:
                a = a.astype(self._dtype)
            if rows is None:
                rows = a.shape[0]
            elif a.shape[0] != rows:
                raise MXNetError(
                    f"serving: model '{self.name}' inputs disagree on the "
                    f"batch axis ({a.shape[0]} vs {rows})")
            arrs.append(a)
        if not rows:
            # a zero-row batch cannot pad up to a bucket — it would
            # compile a novel (0, ...) program and return nothing
            raise MXNetError(
                f"serving: model '{self.name}' request has no rows")
        return rows, arrs

    def pad_rows(self, arrs, rows, bucket):
        """Pad each array from `rows` up to `bucket` by replicating the
        final row (masking: the pad rows are never returned).  Same
        padding `io.pad_to_bucket` gives `Module.predict` batches."""
        if rows == bucket:
            return arrs
        from ..io import _pad_rows
        return [_pad_rows(a, bucket - rows) for a in arrs]

    def infer(self, inputs, block=True):
        """The single-request path: pad to the nearest bucket, run the
        shared compiled program, return per-output NDArrays with the pad
        rows sliced off.  Safe from any thread."""
        rows, arrs = self.prepare_rows(inputs)
        bucket = self.bucket_for(rows)
        if bucket is None:
            raise MXNetError(
                f"serving: model '{self.name}' request batch {rows} exceeds "
                f"max bucket {self.max_batch_size}")
        outs = self.run_bucket(self.pad_rows(arrs, rows, bucket), bucket)
        if block:
            import jax
            jax.block_until_ready(outs)
        return [NDArray(o[:rows], ctx=self._ctx) for o in outs]

    def infer_exact(self, inputs):
        """Run at EXACTLY the declared `data_shapes` — no batch-axis
        semantics, no padding, outputs unsliced.  The C-predict ABI path:
        its inputs may not share a batch axis at all (e.g. a (8, 784)
        data input next to a (1, 256) state input), which the old
        `simple_bind` contract allowed; still one program in the shared
        cache."""
        arrs = []
        for n in self.data_names:
            v = inputs[n] if isinstance(inputs, dict) else \
                inputs[self.data_names.index(n)]
            a = _np.asarray(v, self._dtype).reshape(
                self._declared_shapes[n])
            arrs.append(a)
        outs = self._run(arrs, self._extras(dict(self._declared_shapes)))
        return [NDArray(o, ctx=self._ctx) for o in outs]

    # -- params / monitoring -------------------------------------------------
    def set_params(self, arg_params, aux_params=None):
        """(Hot-)swap the parameter set; in-flight dispatches finish
        against the snapshot they captured, and the program cache is
        untouched (same shapes, new constants)."""
        # aux shapes are batch-independent; infer at the DECLARED shapes,
        # which are always self-consistent — bucketizing every input's
        # leading dim here would reject exact-mode (C ABI) models whose
        # inputs legitimately do not share a batch axis
        _, _, aux_shapes = self._symbol.infer_shape(
            **dict(self._declared_shapes))
        self._infer.set_params(
            arg_params or {}, aux_params or {},
            aux_shapes=dict(zip(self._symbol.list_auxiliary_states(),
                                aux_shapes)))
        self._extra_cache.clear()   # the extra partition may have moved

    def set_monitor_callback(self, callback, monitor_all=False):
        """`Monitor.install` entry point (the serving executor face of
        `Executor.set_monitor_callback`): `callback(name, NDArray)` fires
        per output over the BATCHED outputs of every executed bucket."""
        del monitor_all
        self._monitor = callback

    def install_monitor(self, mon):
        """Install a `monitor.Monitor` on the request path."""
        mon.install(self)
        return mon

    # the Monitor drives tic/toc over installed "executors"; serving has
    # no persistent arg arrays to wait on, so expose empty views
    arg_arrays = ()

    @property
    def arg_dict(self):
        return {}

    @property
    def audit_key(self):
        return self._infer.audit_key

    def program_count(self):
        return self._infer.program_count()

    def export_programs(self, directory):
        """Serialize the compiled bucket ladder into `directory` as
        program-cache entries — ship them with a checkpoint
        (``programs/``) or a container image and the next server's
        `warmup()` performs zero XLA compilations."""
        return self._infer.export_programs(directory)
