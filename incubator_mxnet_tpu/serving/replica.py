"""Replica handles: the units the `ReplicaRouter` spreads requests over.

A replica is one independently-failing copy of a served model.  Two
concrete kinds share the `Replica` contract:

* `LocalReplica` — an in-process `ServedModel` + `MicroBatcher` pair
  (its own parameter copy, its own breaker-visible failure domain).
  N local replicas of one symbol share the unified program cache —
  the graph hash is identical — so replicas 2..N warm with ZERO XLA
  compiles.
* `RemoteReplica` — a subprocess worker (`serving.worker`) driven over
  the sequence-numbered `dist.transport` frames.  The process boundary
  makes SIGKILL-grade death real: the router's failover path is tested
  against actual dead processes, not simulations.  Requests carry the
  router's request id and the worker deduplicates on it, so a resend
  after a torn connection can never execute twice on that worker.

The contract the router relies on:

* ``submit(inputs, timeout_ms, rid)`` returns a Future; the future
  fails with `ReplicaLostError` when the replica dies before resolving
  it (the router's failover trigger — anything else is a caller error
  that would fail identically on every replica).
* ``heartbeat()`` is a cheap liveness check; ``probe()`` is the
  deepcheck — a real bucket-1 inference through the compiled ladder.
* ``swap(...)`` replaces the parameter set in place (same shapes, same
  programs: the program cache is untouched, so a swap costs zero XLA
  compiles).  ``version`` counts committed swaps.
* ``outstanding()`` / ``estimated_wait_s()`` drive least-loaded
  dispatch and priority shedding.
"""
from __future__ import annotations

import os
import queue as _queue
import subprocess
import sys
import threading
import time

from concurrent.futures import Future

import numpy as _np

from ..analysis import locks as _locks
from ..base import MXNetError

__all__ = ["Replica", "LocalReplica", "RemoteReplica", "ReplicaLostError",
           "worker_argv", "launch_worker"]


class ReplicaLostError(MXNetError):
    """The replica died (process killed, batcher torn down, transport
    gone) before this request resolved.  Structured so the router can
    distinguish "this replica is gone — fail over" from "this request
    is bad — fail it everywhere": `replica_id` names the dead replica,
    `rid` the in-flight request."""

    def __init__(self, replica_id, rid=None, reason=""):
        self.replica_id = str(replica_id)
        self.rid = rid
        super().__init__(
            f"replica '{replica_id}' lost"
            + (f" with request {rid} in flight" if rid else "")
            + (f": {reason}" if reason else "")
            + " — the router fails over to a surviving replica")


class Replica:
    """Shared contract; see the module docstring."""

    replica_id = "?"
    version = 0          # committed weight-swap count

    def submit(self, inputs, timeout_ms=None, rid=None, priority=1):
        raise NotImplementedError

    def heartbeat(self):
        raise NotImplementedError

    def probe(self):
        raise NotImplementedError

    def swap(self, arg_params=None, aux_params=None, checkpoint_dir=None):
        raise NotImplementedError

    def outstanding(self):
        raise NotImplementedError

    def estimated_wait_s(self):
        return None

    # protocol stub: concrete replicas surface through the router's
    # 'router' producer and each ServingMetrics' 'serving.<id>' one
    def stats(self):   # mxlint: disable=untracked-stats
        return {}

    def close(self, drain=True):
        pass


def _load_checkpoint_params(checkpoint_dir):
    """(arg_params, aux_params) from the newest VALID elastic checkpoint
    under `checkpoint_dir` (torn checkpoints are never selected) —
    the swap source shared by both replica kinds."""
    from ..checkpoint import load as _load, latest as _latest
    from ..checkpoint.state import split_params
    path = checkpoint_dir
    if not os.path.exists(os.path.join(path, "manifest.json")):
        found = _latest(path)
        if found is None:
            raise MXNetError(
                f"replica swap: no valid checkpoint under "
                f"{checkpoint_dir!r} (torn checkpoints are never selected)")
        path = found
    data = _load(path)
    return split_params(data.arrays)


class LocalReplica(Replica):
    """In-process replica: one `ServedModel` (its own parameter copy)
    behind its own `MicroBatcher`."""

    def __init__(self, model, replica_id=None, max_batch_size=None,
                 max_queue_latency_ms=2.0, max_queue=256, **batcher_knobs):
        from .batcher import MicroBatcher
        from .metrics import ServingMetrics
        self._model = model
        self.replica_id = str(replica_id if replica_id is not None
                              else f"local/{model.name}")
        self.metrics = ServingMetrics(self.replica_id)
        if not model.warmed:
            model.warmup()
        self._batcher = MicroBatcher(
            model, self.metrics, max_batch_size=max_batch_size,
            max_queue_latency_ms=max_queue_latency_ms, max_queue=max_queue,
            **batcher_knobs)
        self._dead = False
        self._last_reply_t = None   # when a response last resolved

    # -- request path --------------------------------------------------------
    def submit(self, inputs, timeout_ms=None, rid=None, priority=1):
        if self._dead:
            raise ReplicaLostError(self.replica_id, rid,
                                   "replica was killed")
        try:
            inner = self._batcher.submit(inputs, timeout_ms=timeout_ms,
                                         priority=priority)
        except MXNetError as exc:
            if self._dead or "draining" in str(exc):
                raise ReplicaLostError(self.replica_id, rid,
                                       str(exc)) from exc
            raise
        # surface the batcher's shutdown sweep as REPLICA LOSS: a killed
        # replica fails its queued requests with a shutdown error, and
        # the router must read that as "this replica is gone, fail the
        # request over", not "this request is bad"
        out = Future()
        out.request_id = rid

        def _chain(f, out=out, rid=rid):
            self._last_reply_t = time.monotonic()
            try:
                res = f.result()
            except MXNetError as exc:
                s = str(exc)
                lost = self._dead and ("shut down" in s or "draining" in s)
                try:
                    out.set_exception(
                        ReplicaLostError(self.replica_id, rid, s)
                        if lost else exc)
                except Exception:
                    pass
                return
            except Exception as exc:
                try:
                    out.set_exception(exc)
                except Exception:
                    pass
                return
            try:
                out.set_result(res)
            except Exception:
                pass

        inner.add_done_callback(_chain)
        return out

    # -- health --------------------------------------------------------------
    def heartbeat(self):
        if self._dead or not self._batcher._thread.is_alive():
            raise ReplicaLostError(self.replica_id,
                                   reason="batcher worker is gone")
        return {"outstanding": self.outstanding(), "version": self.version}

    def probe(self):
        """Deepcheck: a real inference through the smallest bucket."""
        self.heartbeat()
        model = self._model
        inputs = [_np.zeros((1,) + model._sample_shapes[n], model._dtype)
                  for n in model.data_names]
        model.infer(inputs)
        return {"programs": model.program_count(), "version": self.version}

    # -- swap ----------------------------------------------------------------
    def swap(self, arg_params=None, aux_params=None, checkpoint_dir=None):
        if checkpoint_dir is not None:
            arg_params, aux_params = _load_checkpoint_params(checkpoint_dir)
        self._model.set_params(arg_params, aux_params)
        self.version += 1
        return self.version

    # -- load ----------------------------------------------------------------
    def outstanding(self):
        return self._batcher._outstanding

    def estimated_wait_s(self):
        """What a new request would wait here: the batcher's queue-model
        estimate, floored by the observed response-latency EWMA — the
        queue model alone is blind to host scheduling overhead, which
        dominates exactly when the fleet is overloaded.  On an EMPTY
        replica the floor decays with the age of the last response: the
        EWMA cannot decay on its own (it only updates on responses),
        and holding it would wedge the fleet autoscaler's idle
        detection forever after an overload burst."""
        est = self._batcher.estimated_wait_s()
        lat = self.metrics.avg_latency_s()
        if lat is not None and self.outstanding() == 0:
            # empty replica: decay the floor with the age of the last
            # response (1s half-life, same as RemoteReplica) — an
            # abrupt drop would collapse the fleet admission signal on
            # momentary empty instants mid-flood, while no decay at
            # all wedges idle detection forever
            last = self._last_reply_t
            age = 0.0 if last is None else time.monotonic() - last
            lat = lat * 0.5 ** age
        if est is None:
            return lat
        return est if lat is None else max(est, lat)

    # registered by this replica's ServingMetrics ('serving.<id>')
    def stats(self):   # mxlint: disable=untracked-stats
        snap = self.metrics.snapshot()
        snap["version"] = self.version
        return snap

    def close(self, drain=True):
        self._dead = True
        self._batcher.close(drain=drain)

    def kill(self):
        """Abrupt death (tests/chaos): queued requests fail with the
        shutdown error — the router reads it as replica loss and fails
        them over.  A batch already executing completes (its requesters
        were served before the death)."""
        self._dead = True
        try:
            self._batcher.kill()
        except MXNetError:
            pass


def worker_argv(*, prefix=None, epoch=0, symbol_file=None,
                checkpoint_dir=None, data_shapes, buckets=(1, 2, 4, 8),
                name="model", host="127.0.0.1", port=0):
    """The `serving.worker` command line for one replica — the single
    place the worker CLI contract is spelled, shared by
    `RemoteReplica.spawn` (local subprocess) and the fleet host daemon
    (`serving.hostd`, spawning on ITS host)."""
    shapes = ";".join("%s=%s" % (n, ",".join(str(d) for d in s))
                      for n, s in data_shapes)
    cmd = [sys.executable, "-m", "incubator_mxnet_tpu.serving.worker",
           "--name", str(name), "--data-shapes", shapes,
           "--buckets", ",".join(str(b) for b in buckets),
           "--host", str(host), "--port", str(int(port))]
    if prefix is not None:
        cmd += ["--prefix", prefix, "--epoch", str(epoch)]
    if symbol_file is not None:
        cmd += ["--symbol-file", symbol_file]
    if checkpoint_dir is not None:
        cmd += ["--checkpoint-dir", checkpoint_dir]
    return cmd


def launch_worker(cmd, *, env=None, name="model", ready_timeout=240.0,
                  launch=None, tag=None, port_prefix="REPLICA_PORT",
                  ready_prefix="REPLICA_READY", start_new_session=False,
                  thread_prefix="mx-replica"):
    """Run one worker argv and wait for its readiness handshake.
    Returns ``(proc, port, ready_info)`` where ``ready_info`` is the
    parsed ``REPLICA_READY`` evidence (programs / compiles / disk_hits
    — the zero-compile spin-up cert chaos, bench, and the fleet
    autoscaler all read).  ``launch(cmd, env) -> Popen`` overrides the
    default local `subprocess.Popen` (remote-exec hook).  The line
    prefixes are parameters so the fleet host daemon's handshake
    (``HOSTD_PORT`` / ``HOSTD_READY``) shares this one implementation;
    ``start_new_session`` puts the child in its own process group (the
    daemon + its workers die together under a group SIGKILL).

    ``ready_timeout`` is enforced even when the child stays alive but
    SILENT (wedged on a hung checkpoint read): a deadline timer kills
    it, which unblocks the pipe read."""
    full_env = dict(os.environ, **(env or {}))
    if launch is not None:
        proc = launch(cmd, full_env)
    else:
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True,
                                env=full_env,
                                start_new_session=start_new_session)
    port = None
    ready_info = {}
    timed_out = threading.Event()

    def _deadline_kill():
        timed_out.set()
        proc.kill()

    timer = threading.Timer(float(ready_timeout), _deadline_kill)
    timer.daemon = True
    timer.start()
    try:
        while True:
            line = proc.stdout.readline()
            if not line:
                if timed_out.is_set():
                    break
                raise MXNetError(
                    f"worker '{name}' exited during startup "
                    f"(rc={proc.poll()})")
            if line.startswith(port_prefix + " "):
                port = int(line.split()[1])
            elif line.startswith(ready_prefix):
                # "REPLICA_READY programs=N compiles=K disk_hits=D":
                # the zero-compile spin-up evidence (chaos/bench read it)
                for tok in line.split()[1:]:
                    k, _, v = tok.partition("=")
                    if v.isdigit():
                        ready_info[k] = int(v)
                break
    finally:
        timer.cancel()
    if port is None or timed_out.is_set():
        proc.kill()
        raise MXNetError(
            f"worker '{name}' did not complete its readiness handshake "
            f"within {ready_timeout:g}s")
    # drain the pipe in the background or the worker blocks on a
    # full stdout once it starts logging
    threading.Thread(target=lambda: proc.stdout.read(),
                     daemon=True,
                     name=f"{thread_prefix}-{tag or name}-stdout").start()
    return proc, port, ready_info


class RemoteReplica(Replica):
    """Subprocess replica over the seq-numbered dist transport.

    ``concurrency`` dispatch threads each own one `Channel` (channels
    are serial by design), so up to that many requests are on the wire
    at once; the rest wait in a bounded local queue.  The worker side
    coalesces nothing — each request is one device dispatch — so the
    local queue length drives the load estimate."""

    def __init__(self, host, port, replica_id=None, process=None,
                 concurrency=2, max_queue=256, timeout=None,
                 control_timeout=5.0):
        self.replica_id = str(replica_id if replica_id is not None
                              else f"remote/{host}:{port}")
        self.host, self.port = host, int(port)
        self.process = process       # Popen when spawn()ed (chaos kills it)
        self._q = _queue.PriorityQueue(maxsize=int(max_queue))
        self._seq_counter = 0
        self._lost = threading.Event()
        self._inflight = {}          # rid -> _Pending (on the wire)
        self._lock = _locks.make_lock("serving.replica")
        self._ewma_s = None          # recent per-request round-trip
        self._last_reply_t = None    # when the EWMA last saw a response
        self._chans = []
        self._threads = []
        # the control channel answers in microseconds or the worker is
        # in trouble: a SHORT timeout keeps one wedged (but connected)
        # worker from pinning the router's health loop for minutes —
        # the slow probe surfaces as suspicion, not a long stall
        self._control = self._make_channel(control_timeout)
        for i in range(int(concurrency)):
            chan = self._make_channel(timeout)
            self._chans.append(chan)
            t = threading.Thread(target=self._dispatch_loop, args=(chan,),
                                 daemon=True,
                                 name=f"mx-replica-{self.replica_id}-{i}")
            t.start()
            self._threads.append(t)

    def _make_channel(self, timeout):
        from ..dist.transport import Channel
        from ..resilience import RetryPolicy
        # short reconnect budget: a dead worker should be DIAGNOSED in
        # ~a second so failover starts, not nursed for minutes — the
        # router's re-dispatch is the real retry (worker-side rid dedup
        # keeps a transport-level resend from executing twice)
        return Channel(self.host, self.port, timeout=timeout,
                       connect_wait=10.0,
                       retry=RetryPolicy(max_attempts=2, base_delay=0.05,
                                         max_delay=0.2))

    @classmethod
    def spawn(cls, *, prefix=None, epoch=0, symbol_file=None,
              checkpoint_dir=None, data_shapes, buckets=(1, 2, 4, 8),
              name="model", replica_id=None, env=None, concurrency=2,
              ready_timeout=240.0, host="127.0.0.1", launch=None):
        """Launch a `serving.worker` subprocess and connect to it.  The
        worker inherits ``MXNET_PROGRAM_CACHE_DIR`` (when set), so every
        replica after the first warms from the shared disk tier with
        zero XLA compiles.

        ``host`` is the address the worker binds AND the address this
        handle connects to (default localhost, so existing callers and
        artifacts are unchanged).  ``launch`` is the launch-command hook
        for remote execution: a callable ``launch(cmd, env) -> Popen``
        (text mode, stdout piped) that runs the worker argv on the
        target host — e.g. by prefixing an ssh invocation — instead of
        the default local ``subprocess.Popen``.  Cross-host *fleets*
        should prefer `serving.fleet.AgentHost`, which delegates the
        spawn to a host daemon and reuses this module's launch helper
        on the far side."""
        cmd = worker_argv(prefix=prefix, epoch=epoch,
                          symbol_file=symbol_file,
                          checkpoint_dir=checkpoint_dir,
                          data_shapes=data_shapes, buckets=buckets,
                          name=name, host=host)
        proc, port, ready_info = launch_worker(
            cmd, env=env, name=name, ready_timeout=ready_timeout,
            launch=launch, tag=replica_id or name)
        self = cls(host, port, replica_id=replica_id, process=proc,
                   concurrency=concurrency)
        self.ready_info = ready_info
        return self

    # -- request path --------------------------------------------------------
    class _Pending:
        __slots__ = ("msg", "future", "rid", "t_enqueue")

        def __init__(self, msg, rid):
            self.msg = msg
            self.rid = rid
            self.future = Future()
            self.t_enqueue = time.monotonic()

    def submit(self, inputs, timeout_ms=None, rid=None, priority=1):
        if self._lost.is_set():
            raise ReplicaLostError(self.replica_id, rid)
        # host-normalize so only numpy crosses the transport
        to_np = lambda v: v.asnumpy() if hasattr(v, "asnumpy") \
            else _np.asarray(v)
        arrs = {k: to_np(v) for k, v in inputs.items()} \
            if isinstance(inputs, dict) else [to_np(v) for v in inputs]
        msg = {"cmd": "infer", "rid": rid, "inputs": arrs,
               "timeout_ms": timeout_ms}
        from ..obs import trace as _obs_trace
        tr = _obs_trace.current_frame()
        if tr is not None:
            # captured on the SUBMITTING thread: the dispatch loop that
            # puts this frame on the wire runs where contextvars are
            # blind — the channel's rpc span parents to this instead
            msg["tr"] = tr
        pend = self._Pending(msg, rid)
        with self._lock:
            self._seq_counter += 1
            seq = self._seq_counter
        try:
            # same dispatch-rank ordering as the batcher: interactive
            # work never waits behind an admitted best-effort burst
            self._q.put_nowait((int(priority), seq, pend))
        except _queue.Full:
            raise MXNetError(
                f"replica '{self.replica_id}' queue is full — "
                "backpressure, retry later") from None
        return pend.future

    def _dispatch_loop(self, chan):
        while not self._lost.is_set():
            try:
                pend = self._q.get(timeout=0.05)[2]
            except _queue.Empty:
                continue
            if pend.future.cancelled() or \
                    not pend.future.set_running_or_notify_cancel():
                continue
            with self._lock:
                self._inflight[pend.rid] = pend
            try:
                reply = chan.request(pend.msg)
            except Exception as exc:
                # fail THIS pend explicitly first: a concurrent
                # dispatch thread may already have run _mark_lost (its
                # sweep could miss a pend between queue-pop and
                # _inflight insert), and _mark_lost early-returns once
                # _lost is set — the current request must never be
                # left unresolved
                reason = f"{type(exc).__name__}: {exc}"
                with self._lock:
                    self._inflight.pop(pend.rid, None)
                try:
                    pend.future.set_exception(
                        ReplicaLostError(self.replica_id, pend.rid,
                                         reason))
                except Exception:
                    pass
                self._mark_lost(reason)
                return
            with self._lock:
                self._inflight.pop(pend.rid, None)
            rt = time.monotonic() - pend.t_enqueue
            self._ewma_s = rt if self._ewma_s is None \
                else 0.8 * self._ewma_s + 0.2 * rt
            self._last_reply_t = time.monotonic()
            try:
                if "error" in reply:
                    pend.future.set_exception(MXNetError(reply["error"]))
                else:
                    from ..ndarray.ndarray import NDArray
                    pend.future.set_result(
                        [NDArray(_np.asarray(o)) for o in reply["outs"]])
            except Exception:
                pass   # caller cancelled meanwhile

    def _mark_lost(self, reason):
        """Transport-level death: fail everything this replica holds so
        the router's failover callbacks fire at once."""
        if self._lost.is_set():
            return
        self._lost.set()
        with self._lock:
            inflight, self._inflight = dict(self._inflight), {}
        for rid, pend in inflight.items():
            try:
                pend.future.set_exception(
                    ReplicaLostError(self.replica_id, rid, reason))
            except Exception:
                pass
        while True:
            try:
                pend = self._q.get_nowait()[2]
            except _queue.Empty:
                break
            try:
                pend.future.set_exception(
                    ReplicaLostError(self.replica_id, pend.rid, reason))
            except Exception:
                pass

    # -- health --------------------------------------------------------------
    def _control_request(self, msg):
        if self._lost.is_set():
            raise ReplicaLostError(self.replica_id)
        try:
            reply = self._control.request(msg)
        except TimeoutError:
            # slow-but-connected is SUSPICION evidence, not death: the
            # health loop degrades the replica's preference and only
            # the liveness deadline (continued silence) evicts it
            raise
        except Exception as exc:
            raise ReplicaLostError(
                self.replica_id,
                reason=f"{type(exc).__name__}: {exc}") from exc
        if "error" in reply:
            raise MXNetError(reply["error"])
        return reply

    def heartbeat(self):
        return self._control_request({"cmd": "hb"})

    def probe(self):
        return self._control_request({"cmd": "probe"})

    def swap(self, arg_params=None, aux_params=None, checkpoint_dir=None):
        if checkpoint_dir is None:
            raise MXNetError(
                f"replica '{self.replica_id}': remote swap needs a "
                "checkpoint_dir the worker can read (shipping raw param "
                "tensors over the control channel is not supported)")
        reply = self._control_request({"cmd": "swap",
                                       "checkpoint_dir": checkpoint_dir})
        self.version = int(reply["version"])
        return self.version

    # -- load ----------------------------------------------------------------
    def outstanding(self):
        with self._lock:
            return self._q.qsize() + len(self._inflight)

    def estimated_wait_s(self):
        if self._ewma_s is None:
            return None
        outstanding = self.outstanding()
        if outstanding == 0:
            # same wedge as LocalReplica's EWMA floor: the round-trip
            # EWMA is measured from enqueue (it INCLUDES queue wait)
            # and only updates on responses, so on an EMPTY replica it
            # is a memory of traffic that already ended and would hold
            # a remembered overload forever, blocking the fleet
            # autoscaler's idle detection.  The VIEW decays with the
            # age of the last response (1s half-life, no mutation —
            # a read-rate-dependent decay would collapse the shared
            # measurement admission shedding floors on): a momentary
            # empty instant mid-flood reads essentially the full
            # floor, real silence reaches any idle threshold within
            # seconds.
            last = self._last_reply_t
            age = 0.0 if last is None else time.monotonic() - last
            return self._ewma_s * 0.5 ** age
        return self._ewma_s * (outstanding + 1) / max(
            len(self._chans), 1)

    # a remote fetch, not a local producer: the worker process's own
    # registry answers its scrapes (see scrape() below)
    def stats(self):   # mxlint: disable=untracked-stats
        try:
            return self._control_request({"cmd": "stats"})
        except (ReplicaLostError, MXNetError):
            return {"lost": True}

    def scrape(self):
        """The worker process's telemetry snapshot ({"values", "prom"})
        over the control channel — the fleet's per-replica scrape leg."""
        reply = self._control_request({"cmd": "metrics"})
        return {"values": dict(reply.get("values") or {}),
                "prom": reply.get("prom", "")}

    def close(self, drain=True):
        if not self._lost.is_set() and drain:
            deadline = time.monotonic() + 30
            while self.outstanding() and time.monotonic() < deadline:
                time.sleep(0.01)
        try:
            if not self._lost.is_set():
                self._control.bare_request({"cmd": "stop"})
        except Exception:
            pass
        self._mark_lost("replica closed")
        for chan in self._chans + [self._control]:
            try:
                chan.close()
            except Exception:
                pass
        if self.process is not None:
            try:
                self.process.wait(timeout=10)
            except Exception:
                self.process.kill()

    def kill(self):
        """SIGKILL the worker process (chaos): no flush, no unwinding."""
        if self.process is not None:
            self.process.kill()
