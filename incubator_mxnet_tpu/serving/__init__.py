"""TPU-native inference serving: dynamic batching over a shape-bucketed
program cache.

The missing request path for the north star's "heavy traffic" goal: the
repo could train, checkpoint, and analyze, but inference was a caller's
`Module.forward` loop.  This package serves models the way the hardware
wants to be driven — on TPU every NOVEL request shape stalls the stream
behind a multi-second XLA compile, so shapes are restricted to a fixed
bucket ladder compiled at warmup, and concurrent requests are coalesced
into bucket-sized batches (the MXNet-paper engine's concurrent-executor
role + the TensorFlow-paper production recipe of batched compiled
subgraphs, arxiv 1512.01274 / 1605.08695).

Layers:

* `ServedModel` (model.py) — a loaded model (symbol JSON + params, from
  classic prefix checkpoints or elastic ``checkpoint/`` dirs) compiled
  over a bucket ladder via the shared `fused.FusedInference` program
  cache; `infer()` is the synchronous single-request path (the C-predict
  ABI routes here).
* `MicroBatcher` (batcher.py) — bounded queue + coalescing worker:
  ``max_batch_size`` / ``max_queue_latency_ms`` batching knobs, padding
  to the nearest bucket, per-request deadlines, backpressure, graceful
  drain.
* `ModelServer` (server.py) — multi-model front end with hot
  load/unload that never drops in-flight requests.
* `ServingMetrics` (metrics.py) — QPS, p50/p99 latency (bounded
  reservoir), per-priority-class counters, batch occupancy, queue
  depth; batches land in the profiler trace when one is running.
* `ReplicaRouter` (router.py) over `Replica` handles (replica.py) — the
  availability layer: least-loaded health/breaker-aware dispatch over N
  replicas (in-process `LocalReplica`s and/or `RemoteReplica`
  subprocess workers on the dist transport, worker.py), idempotent
  failover of in-flight requests off a dead replica, rolling hot
  weight-swap with zero dropped requests and zero XLA compiles, and
  priority classes (interactive/batch/best_effort) that shed lowest
  first under overload.
* `DecodeEngine` / `DecodeReplica` (decode.py) — the state-carrying
  request path: continuous-batching autoregressive LM decode over a
  fixed slot pool and donated KV-cache carry (llm.decode_core), per-
  bucket prefill + one fixed-shape decode-step program, admitting and
  evicting sequences every tick with zero steady-state recompiles;
  the Replica face plugs it into the router/fleet layers unchanged.
* `FleetManager` (fleet.py) over `FleetHost` handles + `serving.hostd`
  host agents — the fleet layer: host-aware anti-affinity placement,
  host liveness through the SAME `dist.membership` table the elastic
  trainer uses (a dead HOST marks all its replicas dead at once and
  backfills on survivors), and an SLO-driven autoscaler fed by the
  router's admission est-wait signal (sustained breach spawns a
  zero-compile warm replica, sustained idle retires one through the
  drain path; hysteresis + cooldown + a min/max budget make it
  flap-proof).

Minimal server::

    import incubator_mxnet_tpu as mx
    srv = mx.serving.ModelServer(max_queue_latency_ms=2.0)
    srv.load_model("mnist", prefix="model", epoch=3,
                   data_shapes=[("data", (1, 784))], buckets=(1, 8, 32))
    out = srv.predict("mnist", {"data": x}, timeout_ms=50)[0]
    srv.shutdown(drain=True)

The recompile auditor (`analysis.recompile`) certifies the warmup
contract: every bucket is registered before compiling, so any signature
it reports afterwards is a real post-warmup recompile.
"""
from __future__ import annotations

from .model import ServedModel, DEFAULT_BUCKETS
from .batcher import MicroBatcher
from .server import ModelServer
from .metrics import ServingMetrics, LatencyReservoir
from .replica import (Replica, LocalReplica, RemoteReplica,
                      ReplicaLostError)
from .router import ReplicaRouter, SwapInProgressError, PRIORITIES
from .fleet import (FleetManager, Autoscaler, ReplicaSpec, FleetHost,
                    InProcessHost, AgentHost)
from .decode import DecodeEngine, DecodeReplica

__all__ = ["ServedModel", "MicroBatcher", "ModelServer", "ServingMetrics",
           "LatencyReservoir", "Replica", "LocalReplica", "RemoteReplica",
           "ReplicaLostError", "ReplicaRouter", "SwapInProgressError",
           "PRIORITIES",
           "DEFAULT_BUCKETS", "FleetManager", "Autoscaler", "ReplicaSpec",
           "FleetHost", "InProcessHost", "AgentHost", "DecodeEngine",
           "DecodeReplica"]
