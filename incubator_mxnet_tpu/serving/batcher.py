"""Dynamic micro-batching: coalesce concurrent requests into bucket-sized
device batches.

The scheduler is a bounded request queue plus one worker thread per model.
The worker takes the oldest request, then keeps admitting more until either
the batch would exceed ``max_batch_size`` or ``max_queue_latency_ms`` has
elapsed since the FIRST request of the batch arrived — the classic
latency/throughput knob: 0 serves every request alone, a few milliseconds
lets a concurrency-N client fill whole buckets.  The coalesced rows are
padded to the nearest bucket (`ServedModel.pad_rows`), executed as ONE
compiled program, and scattered back to per-request futures by row range,
so each caller sees exactly its own rows in submission order.

Unhappy paths are first-class:

* per-request deadlines — a request still queued past its deadline gets a
  clean `MXNetError` naming the model and the timeout, and never reaches
  the device;
* backpressure — a full queue rejects `submit` immediately instead of
  growing an unbounded backlog;
* graceful drain — `close(drain=True)` stops admissions, completes every
  queued request, then joins the worker (model unload/swap without
  dropping in-flight work); a ``drain timeout`` turns a wedged drain into
  a structured error listing the still-pending request ids.

Overload control (the resilience layer's serving half):

* deadline-aware shedding — a request whose deadline cannot be met given
  the current queue depth and recent batch times is rejected BEFORE it
  queues (work that will time out anyway must not consume device time
  other requests could meet their deadlines with);
* a per-model circuit breaker — consecutive failed batches open it, and
  while open `submit` fails fast; after the reset window one half-open
  probe batch tests recovery;
* bounded execution retries — transient batch failures retry under a
  `RetryPolicy`, recorded in the metrics retry histogram.
"""
from __future__ import annotations

import queue as _queue
import threading
import time

from concurrent.futures import Future

import numpy as _np

from ..analysis import locks as _locks
from ..analysis import tsan as _tsan
from ..base import MXNetError
from ..obs import trace as _obs_trace
from ..resilience import CircuitBreaker, faults as _faults

__all__ = ["MicroBatcher"]


class _Request:
    __slots__ = ("arrs", "rows", "deadline", "timeout_ms", "future",
                 "t_enqueue", "rid", "prio", "tr")

    def __init__(self, arrs, rows, timeout_ms, rid, prio=1):
        self.arrs = arrs
        self.rows = rows
        self.timeout_ms = timeout_ms
        self.rid = rid
        self.prio = int(prio)
        self.t_enqueue = time.monotonic()
        self.deadline = (self.t_enqueue + timeout_ms / 1e3
                         if timeout_ms is not None else None)
        self.future = Future()
        # trace context captured on the SUBMITTING thread: the batch
        # executes on the worker thread, where contextvars are blind
        self.tr = _obs_trace.current_frame()


class MicroBatcher:
    """The per-model request queue + coalescing worker."""

    def __init__(self, model, metrics, max_batch_size=None,
                 max_queue_latency_ms=2.0, max_queue=256,
                 breaker_threshold=None, breaker_reset_s=None,
                 retry_policy=None):
        from .. import config as _config
        self._model = model
        self._metrics = metrics
        self.max_batch_size = min(int(max_batch_size or model.max_batch_size),
                                  model.max_batch_size)
        self.max_queue_latency_ms = float(max_queue_latency_ms)
        self.max_queue = int(max_queue)
        # priority queue keyed (prio rank, arrival seq): the router's
        # QoS classes hold DISPATCH order too — an admitted best-effort
        # burst must not sit ahead of interactive work (admission
        # control alone cannot recall what it already let in).  Rank 1
        # is the default, so router-less callers keep plain FIFO.
        self._q = _queue.PriorityQueue(maxsize=self.max_queue)
        self._carry = None         # request admitted but deferred to the
                                   # next batch (would overflow this one)
        self._outstanding = 0
        self._lock = _locks.make_lock("serving.batcher")
        self._idle = _locks.make_condition(self._lock)
        self._stop = threading.Event()
        self._killed = False       # abrupt death: sweep, don't execute
        self._draining = threading.Event()
        self._paused = threading.Event()
        self._monitor = None       # a monitor.Monitor driven per batch
        self._breaker = CircuitBreaker(
            failure_threshold=int(
                breaker_threshold if breaker_threshold is not None
                else _config.get("MXNET_SERVING_BREAKER_THRESHOLD")),
            reset_timeout=float(
                breaker_reset_s if breaker_reset_s is not None
                else _config.get("MXNET_SERVING_BREAKER_RESET_S")))
        self._retry = retry_policy     # None = batch failures don't retry
        self._rid_counter = 0
        self._pending = {}             # rid -> _Request (admitted, unresolved)
        _tsan.instrument(self, f"serving.batcher[{model.name}]")
        self._thread = threading.Thread(
            target=self._worker, daemon=True,
            name=f"mx-serving-{model.name}")
        self._thread.start()

    # -- client side ---------------------------------------------------------
    def estimated_wait_s(self):
        """How long a newly queued request will wait before executing,
        from the queue depth and the EWMA of recent batch times.  None
        before the first executed batch (no estimate, no shedding)."""
        batch_s = self._metrics.avg_batch_s()
        if batch_s is None:
            return None
        batches_ahead = -(-(self._q.qsize() + 1) // self.max_batch_size)
        return batch_s * batches_ahead

    def submit(self, inputs, timeout_ms=None, priority=1):
        """Enqueue one request; returns a Future resolving to the list of
        per-output NDArrays for exactly this request's rows.
        ``priority`` is the dispatch rank (0 = interactive first, 1 =
        default, 2 = best-effort last); equal ranks stay FIFO."""
        if self._draining.is_set() or self._stop.is_set():
            raise MXNetError(f"serving: model '{self._model.name}' is "
                             "draining; not accepting requests")
        if not self._breaker.allow():
            self._metrics.record_breaker_reject()
            self._metrics.set_breaker_state(self._breaker.state)
            raise MXNetError(
                f"serving: model '{self._model.name}' circuit breaker is "
                f"{self._breaker.state} after "
                f"{self._breaker.failure_threshold} consecutive batch "
                "failures — failing fast; recovery probes run every "
                f"{self._breaker.reset_timeout:g}s")
        # every rejection below must hand back a half-open probe token
        # `allow()` may just have consumed, or the breaker wedges
        queued = False
        try:
            if timeout_ms is not None:
                # deadline-aware shedding: a request that cannot make its
                # deadline must be refused NOW, before it consumes queue
                # slots and device time only to time out anyway
                est = self.estimated_wait_s()
                if est is not None and est > timeout_ms / 1e3:
                    self._metrics.record_shed()
                    raise MXNetError(
                        f"serving: model '{self._model.name}' is "
                        f"overloaded — estimated queue wait "
                        f"{est * 1e3:.0f} ms exceeds this request's "
                        f"{timeout_ms:g} ms deadline (shed before "
                        "queueing)")
            rows, arrs = self._model.prepare_rows(inputs)
            if rows > self.max_batch_size:
                raise MXNetError(
                    f"serving: model '{self._model.name}' request batch "
                    f"{rows} exceeds max_batch_size {self.max_batch_size}")
            if priority >= 2 and \
                    self._q.qsize() >= (self.max_queue * 4) // 5:
                # the top fifth of the queue is reserved for higher
                # classes: a best-effort flood may fill its 80% and
                # bounce, but it can never backpressure the traffic the
                # QoS policy exists to protect.  Rank 0/1 (interactive
                # and default router-less callers) see the full queue.
                self._metrics.record_reject()
                raise MXNetError(
                    f"serving: model '{self._model.name}' queue is past "
                    f"its best-effort high-water mark "
                    f"({(self.max_queue * 4) // 5} of {self.max_queue}) "
                    "— backpressure, retry later")
            with self._lock:
                self._rid_counter += 1
                seq = self._rid_counter   # captured under the lock: the
                # queue tie-break must be unique or heapq falls through
                # to comparing _Request objects
                rid = f"{self._model.name}-{seq}"
                req = _Request(arrs, rows, timeout_ms, rid,
                               prio=priority)
                req.future.request_id = rid
                self._outstanding += 1
                self._pending[rid] = req
            try:
                self._q.put_nowait((req.prio, seq, req))
            except _queue.Full:
                with self._lock:
                    self._outstanding -= 1
                    self._pending.pop(rid, None)
                self._metrics.record_reject()
                raise MXNetError(
                    f"serving: model '{self._model.name}' queue is full "
                    f"({self.max_queue} pending) — backpressure, retry "
                    "later")
            queued = True
        finally:
            if not queued:
                self._breaker.release_probe()
        if self._stop.is_set():
            # raced with close(): the worker may already be gone and the
            # final failure sweep past — sweep again so no future is left
            # unresolved (each request is dequeued exactly once)
            self._sweep_failed()
        self._metrics.record_request(self._q.qsize())
        return req.future

    def pause(self):
        """Stop dispatching (queued requests wait); used while swapping
        weights or in tests that need a deterministically full queue."""
        self._paused.set()

    def resume(self):
        self._paused.clear()

    def install_monitor(self, mon):
        """Drive a `monitor.Monitor` tic/toc around every executed batch
        (the fit loop's idiom, on the request path)."""
        self._model.install_monitor(mon)
        self._monitor = mon

    def pending_request_ids(self):
        """Ids of admitted-but-unresolved requests (drain diagnostics)."""
        with self._lock:
            return sorted(self._pending)

    def close(self, drain=True, timeout=None):
        """Stop the batcher.  With ``drain`` every queued request is
        completed first; without, queued requests fail fast with a
        shutdown error.  A drain that outlives ``timeout`` seconds stops
        anyway and raises a structured error listing the request ids that
        were still pending — a wedged request must not block an unload
        forever."""
        self._draining.set()
        self._paused.clear()   # a paused worker could never drain
        drained = True
        if drain:
            with self._idle:
                drained = self._idle.wait_for(
                    lambda: self._outstanding == 0, timeout=timeout)
        stuck = self.pending_request_ids() if not drained else []
        self._stop.set()
        _tsan.join_thread(self._thread, 10,
                          owner=f"MicroBatcher[{self._model.name}]")
        self._sweep_failed()   # non-drain shutdown: fail what is queued
        if stuck:
            raise MXNetError(
                f"serving: model '{self._model.name}' drain timed out "
                f"after {timeout:g}s with {len(stuck)} request(s) still "
                f"pending: {', '.join(stuck[:16])}"
                + (" ..." if len(stuck) > 16 else "")
                + " — queued ones were failed with a shutdown error; a "
                  "request wedged in execution is abandoned to its future")

    def kill(self):
        """Abrupt death (the replica-failure simulation local replicas
        need): the worker stops WITHOUT executing queued requests — they
        fail with the shutdown error, exactly like a SIGKILLed remote
        worker's queue.  A batch already on the device completes (its
        callers were served before the death)."""
        self._killed = True
        self._draining.set()
        self._stop.set()
        self._paused.clear()
        _tsan.join_thread(self._thread, 10,
                          owner=f"MicroBatcher[{self._model.name}]")
        self._sweep_failed()

    def _sweep_failed(self):
        while True:
            try:
                req = self._q.get_nowait()[2]
            except _queue.Empty:
                return
            self._fail(req, MXNetError(
                f"serving: model '{self._model.name}' shut down before "
                "this request ran"))

    # -- worker side ---------------------------------------------------------
    def _done(self, req):
        with self._idle:
            self._outstanding -= 1
            self._pending.pop(req.rid, None)
            if self._outstanding == 0:
                self._idle.notify_all()

    def _fail(self, req, exc):
        try:
            req.future.set_exception(exc)
        except Exception:   # caller cancelled it meanwhile; nothing to tell
            pass
        self._done(req)

    def _take(self, timeout):
        if self._carry is not None:
            req, self._carry = self._carry, None
            return req
        return self._q.get(timeout=timeout)[2]

    def _worker(self):
        while True:
            try:
                first = self._take(timeout=0.05)
            except _queue.Empty:
                if self._stop.is_set():
                    return
                continue
            while self._paused.is_set() and not self._stop.is_set():
                time.sleep(0.001)
            batch = [first]
            rows = first.rows
            # coalesce until the bucket ladder is full or the oldest
            # request has waited max_queue_latency_ms
            t_close = first.t_enqueue + self.max_queue_latency_ms / 1e3
            while rows < self.max_batch_size:
                if self._carry is None and self._q.empty():
                    with self._lock:
                        quiescent = self._outstanding == len(batch)
                    if quiescent:
                        # every live request is already in hand: nothing
                        # more can arrive until we respond (closed-loop
                        # clients), so waiting out the latency window
                        # would buy batch rows from nobody — dispatch now
                        break
                remaining = t_close - time.monotonic()
                try:
                    # a non-positive remainder still sweeps the queue once
                    # without blocking, so a burst that is ALREADY queued
                    # fills the bucket even at latency 0
                    nxt = self._take(timeout=max(remaining, 0))
                except _queue.Empty:
                    break
                if rows + nxt.rows > self.max_batch_size:
                    self._carry = nxt   # heads the next batch
                    break
                batch.append(nxt)
                rows += nxt.rows
            self._metrics.set_queue_depth(self._q.qsize())
            if self._killed:
                # killed mid-coalesce: nothing else may execute here
                for req in batch:
                    self._fail(req, MXNetError(
                        f"serving: model '{self._model.name}' shut down "
                        "before this request ran"))
                continue
            self._execute(batch)

    def _execute(self, batch):
        model = self._model
        now = time.monotonic()
        live = []
        rows = 0
        for req in batch:
            # marking the future running makes later set_result safe: a
            # cancelled future would otherwise raise InvalidStateError
            # and kill this worker thread for every model client
            if not req.future.set_running_or_notify_cancel():
                self._done(req)
            elif req.deadline is not None and now > req.deadline:
                self._metrics.record_timeout()
                self._fail(req, MXNetError(
                    f"serving: request to model '{model.name}' exceeded "
                    f"its {req.timeout_ms:g} ms deadline in the queue"))
            else:
                live.append(req)
                rows += req.rows
        if not live:
            # the whole batch died before executing (deadline-expired in
            # queue / cancelled): a half-open probe among them never got
            # its trial — hand the token back or the breaker wedges
            self._breaker.release_probe()
            return
        bucket = model.bucket_for(rows)
        arrs = [_np.concatenate(parts) if len(parts) > 1 else parts[0]
                for parts in zip(*(r.arrs for r in live))]
        mon = self._monitor
        delays = self._retry.delays() if self._retry is not None else iter(())
        attempt = 0
        while True:
            # per-attempt clock: the EWMA that drives deadline shedding
            # must reflect a successful execution, not backoff sleeps
            t0 = time.monotonic()
            try:
                _faults.fire("serving.execute", model=model.name,
                             attempt=attempt)
                if mon is not None:
                    mon.tic()
                outs = model.run_bucket(model.pad_rows(arrs, rows, bucket),
                                        bucket)
                import jax
                jax.block_until_ready(outs)
                if mon is not None:
                    mon.toc_print()
                break
            except Exception as exc:
                # transient device/runtime failures retry under the policy
                # (recorded in the retry histogram); exhausted retries fail
                # every future and count one batch failure on the breaker
                delay = next(delays, None)
                if delay is None:
                    self._breaker.record_failure()
                    self._metrics.set_breaker_state(self._breaker.state)
                    err = exc if isinstance(exc, MXNetError) else MXNetError(
                        f"serving: model '{model.name}' batch execution "
                        f"failed: {exc}")
                    for req in live:
                        self._fail(req, err)
                    return
                attempt += 1
                self._metrics.record_retry(attempt)
                _faults.note("retry", site="serving.execute",
                             model=model.name, attempt=attempt)
                time.sleep(delay)
        self._breaker.record_success()
        self._metrics.set_breaker_state(self._breaker.state)
        done = time.monotonic()
        self._metrics.record_batch(rows, bucket, done - t0)
        if _obs_trace.enabled():
            # ONE span per executed batch, parented into the first
            # coalesced request's trace (span emission runs on the
            # serialized batcher worker thread — per-request spans here
            # would tax every request in the queue; the other requests'
            # rids ride in args, and their trees stay rooted at their
            # router.request spans)
            dur_us = int((done - t0) * 1e6)
            _obs_trace.record_span(
                "batcher.execute", time.time_ns() // 1000 - dur_us,
                dur_us, parent=next((r.tr for r in live
                                     if r.tr is not None), None),
                cat="serving", model=model.name, bucket=bucket,
                batch_rows=rows, requests=len(live),
                rids=",".join(str(r.rid) for r in live[:8]))
        ctx = model._ctx
        from ..ndarray.ndarray import NDArray
        off = 0
        for req in live:
            lo, hi = off, off + req.rows
            off = hi
            req.future.set_result(
                [NDArray(o[lo:hi], ctx=ctx) for o in outs])
            self._metrics.record_response(done - req.t_enqueue)
            self._done(req)
