"""Vision datasets (reference `python/mxnet/gluon/data/vision/datasets.py`).

This environment has zero egress, so the download path raises with a clear
message; datasets read pre-downloaded idx/bin files when `root` contains
them.  `SyntheticImageDataset` provides a deterministic stand-in used by the
test suite and benchmarks (same role as the reference CI's cached data).
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ....base import MXNetError
from ....ndarray.ndarray import array
from ..dataset import Dataset, ArrayDataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset", "SyntheticImageDataset"]


class _DownloadedDataset(Dataset):
    def __init__(self, root, transform):
        self._transform = transform
        self._data = None
        self._label = None
        self._root = os.path.expanduser(root)
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST from local idx files (reference `datasets.py:MNIST`)."""

    _train_files = ("train-images-idx3-ubyte", "train-labels-idx1-ubyte")
    _test_files = ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")

    def __init__(self, root="~/.mxnet/datasets/mnist", train=True,
                 transform=None):
        self._train = train
        super().__init__(root, transform)

    def _get_data(self):
        files = self._train_files if self._train else self._test_files
        paths = []
        for f in files:
            found = None
            for cand in (os.path.join(self._root, f),
                         os.path.join(self._root, f + ".gz")):
                if os.path.exists(cand):
                    found = cand
                    break
            if found is None:
                raise MXNetError(
                    f"MNIST file {f} not found under {self._root}. This "
                    "environment has no network access — place the idx files "
                    "there manually, or use "
                    "gluon.data.vision.SyntheticImageDataset for testing.")
            paths.append(found)
        self._data = array(_read_images(paths[0])[..., None])
        self._label = _read_labels(paths[1]).astype(np.int32)


class FashionMNIST(MNIST):
    def __init__(self, root="~/.mxnet/datasets/fashion-mnist", train=True,
                 transform=None):
        super().__init__(root, train, transform)


def _read_images(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        _, n, rows, cols = struct.unpack(">IIII", f.read(16))
        return np.frombuffer(f.read(n * rows * cols),
                             dtype=np.uint8).reshape(n, rows, cols)


def _read_labels(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        _, n = struct.unpack(">II", f.read(8))
        return np.frombuffer(f.read(n), dtype=np.uint8)


class CIFAR10(_DownloadedDataset):
    """CIFAR10 from local bin files (reference `datasets.py:CIFAR10`)."""

    def __init__(self, root="~/.mxnet/datasets/cifar10", train=True,
                 transform=None):
        self._train = train
        self._archive_file_name = "cifar-10-binary"
        super().__init__(root, transform)

    def _file_list(self):
        if self._train:
            return [f"data_batch_{i}.bin" for i in range(1, 6)]
        return ["test_batch.bin"]

    def _get_data(self):
        data = []
        labels = []
        for fname in self._file_list():
            path = os.path.join(self._root, fname)
            if not os.path.exists(path):
                raise MXNetError(
                    f"CIFAR file {fname} not found under {self._root} "
                    "(no network access; place files manually or use "
                    "SyntheticImageDataset).")
            raw = np.fromfile(path, dtype=np.uint8).reshape(-1, 3073)
            labels.append(raw[:, 0])
            data.append(raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
        self._data = array(np.concatenate(data))
        self._label = np.concatenate(labels).astype(np.int32)


class CIFAR100(CIFAR10):
    def __init__(self, root="~/.mxnet/datasets/cifar100", fine_label=False,
                 train=True, transform=None):
        self._fine_label = fine_label
        super(CIFAR10, self).__init__(root, transform)  # skip CIFAR10 init
        self._train = train

    def _file_list(self):
        return ["train.bin" if self._train else "test.bin"]


class ImageRecordDataset(Dataset):
    """Images from a RecordIO file (reference `datasets.py:ImageRecordDataset`)."""

    def __init__(self, filename, flag=1, transform=None):
        from ..dataset import RecordFileDataset
        self._record = RecordFileDataset(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from .... import recordio
        record = self._record[idx]
        header, img = recordio.unpack_img(record, self._flag)
        img = array(img, dtype="uint8")
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self._record)


class ImageFolderDataset(Dataset):
    """folder/label/img.jpg layout (reference `datasets.py:ImageFolderDataset`)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png"]
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                if os.path.splitext(filename)[1].lower() in self._exts:
                    self.items.append((os.path.join(path, filename), label))

    def __getitem__(self, idx):
        from .... import image as img_mod
        with open(self.items[idx][0], "rb") as f:
            img = img_mod.imdecode(f.read(), to_rgb=self._flag)
        label = self.items[idx][1]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)


class SyntheticImageDataset(Dataset):
    """Deterministic synthetic classification images (testing/benchmarks)."""

    def __init__(self, num_samples=1000, shape=(28, 28, 1), num_classes=10,
                 seed=0, transform=None):
        rng = np.random.RandomState(seed)
        protos = rng.randint(0, 255, (num_classes,) + tuple(shape)) \
            .astype(np.uint8)
        self._labels = rng.randint(0, num_classes, num_samples).astype(np.int32)
        noise = rng.randint(-20, 20, (num_samples,) + tuple(shape))
        imgs = protos[self._labels].astype(np.int32) + noise
        self._imgs = np.clip(imgs, 0, 255).astype(np.uint8)
        self._transform = transform

    def __getitem__(self, idx):
        img = array(self._imgs[idx], dtype="uint8")
        label = self._labels[idx]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self._labels)
