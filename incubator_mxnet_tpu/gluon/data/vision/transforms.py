"""Vision transforms (reference `python/mxnet/gluon/data/vision/transforms.py`),
backed by `nd.image.*` ops (reference `src/operator/image/image_random.cc`)."""
from __future__ import annotations

import random as _pyrandom

import numpy as np

from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential
from ....ndarray.ndarray import NDArray, array

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomResizedCrop", "RandomFlipLeftRight", "RandomFlipTopBottom",
           "RandomBrightness", "RandomContrast", "RandomSaturation"]


class Compose(Sequential):
    """Sequentially composed transforms (reference `transforms.py:Compose`)."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return F.Cast(x, dtype=self._dtype)


class ToTensor(Block):
    """HWC uint8 -> CHW float [0,1] (reference `transforms.py:ToTensor`)."""

    def forward(self, x):
        from ....ndarray import image as nd_image
        return nd_image.to_tensor(x)


class Normalize(Block):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = mean
        self._std = std

    def forward(self, x):
        from ....ndarray import image as nd_image
        return nd_image.normalize(x, self._mean, self._std)


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else (size, size)
        self._keep = keep_ratio

    def forward(self, x):
        from .... import image as img_mod
        img = x.asnumpy()
        if self._keep:
            return img_mod.resize_short(img, min(self._size))
        return array(img_mod._resize_np(img, self._size[0], self._size[1]),
                     dtype="uint8" if img.dtype == np.uint8 else "float32")


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else (size, size)

    def forward(self, x):
        from .... import image as img_mod
        return img_mod.center_crop(x, self._size)[0]


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4., 4 / 3.),
                 interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else (size, size)
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        from .... import image as img_mod
        return img_mod.random_size_crop(x, self._size, self._scale,
                                        self._ratio)[0]


class RandomFlipLeftRight(HybridBlock):
    def hybrid_forward(self, F, x):
        from ....ndarray import image as nd_image
        return nd_image.random_flip_left_right(x)


class RandomFlipTopBottom(HybridBlock):
    def hybrid_forward(self, F, x):
        from ....ndarray import image as nd_image
        return nd_image.random_flip_top_bottom(x)


class _RandomJitter(Block):
    def __init__(self, jitter):
        super().__init__()
        self._jitter = jitter

    def _alpha(self):
        return 1.0 + _pyrandom.uniform(-self._jitter, self._jitter)


class RandomBrightness(_RandomJitter):
    def forward(self, x):
        arr = x.asnumpy().astype("float32") * self._alpha()
        return array(np.clip(arr, 0, 255), dtype="float32")


class RandomContrast(_RandomJitter):
    def forward(self, x):
        arr = x.asnumpy().astype("float32")
        mean = arr.mean()
        arr = mean + (arr - mean) * self._alpha()
        return array(np.clip(arr, 0, 255), dtype="float32")


class RandomSaturation(_RandomJitter):
    def forward(self, x):
        arr = x.asnumpy().astype("float32")
        gray = arr.mean(axis=-1, keepdims=True)
        arr = gray + (arr - gray) * self._alpha()
        return array(np.clip(arr, 0, 255), dtype="float32")
