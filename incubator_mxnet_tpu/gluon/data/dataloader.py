"""DataLoader (reference `python/mxnet/gluon/data/dataloader.py:26-112`).

The reference forks multiprocessing workers and ships batches through
CPUShared-memory NDArray pickling.  Here workers are threads: batchify is
numpy (releases the GIL for decode-heavy datasets), there is no fork — the
reference's `pthread_atfork` engine-restart machinery (`initialize.cc:52-66`)
is unnecessary by construction, and batches land directly in host memory
ready for the device transfer.  `num_workers` keeps its meaning as the
prefetch parallelism degree.
"""
from __future__ import annotations

import threading

from ...analysis import locks as _alocks
import queue as _queue

import numpy as np

from ...ndarray.ndarray import NDArray, array
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference `dataloader.py default_batchify_fn`)."""
    if isinstance(data[0], NDArray):
        import jax.numpy as jnp
        return NDArray(jnp.stack([d._data for d in data]),
                       ctx=data[0].context)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = np.asarray(data)
    return array(data, dtype=data.dtype if data.dtype != np.float64
                 else np.float32)


class DataLoader:
    """Loads batches from a Dataset (reference `dataloader.py:DataLoader`)."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=False):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size must be specified unless "
                                 "batch_sampler is specified")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must not be specified if sampler "
                                 "is specified")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError("batch_size, shuffle, sampler and last_batch "
                             "must not be specified if batch_sampler is "
                             "specified.")
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._prefetch = max(0, prefetch or 2 * self._num_workers)

    def __len__(self):
        return len(self._batch_sampler)

    def __iter__(self):
        if self._num_workers == 0:
            for batch_idx in self._batch_sampler:
                yield self._batchify_fn([self._dataset[i] for i in batch_idx])
            return

        # threaded pipeline: workers fetch+batchify, consumer preserves order
        batches = list(self._batch_sampler)
        results = {}
        results_lock = _alocks.make_lock("gluon.dataloader")
        results_ready = _alocks.make_condition(results_lock)
        task_q = _queue.Queue()
        for i, b in enumerate(batches):
            task_q.put((i, b))

        def worker():
            while True:
                try:
                    i, idx = task_q.get_nowait()
                except _queue.Empty:
                    return
                out = self._batchify_fn([self._dataset[j] for j in idx])
                with results_ready:
                    results[i] = out
                    results_ready.notify_all()

        threads = [threading.Thread(target=worker, daemon=True,
                                    name=f"mx-dataloader-worker-{i}")
                   for i in range(self._num_workers)]
        for t in threads:
            t.start()
        for i in range(len(batches)):
            with results_ready:
                while i not in results:
                    results_ready.wait(timeout=60)
                yield results.pop(i)
