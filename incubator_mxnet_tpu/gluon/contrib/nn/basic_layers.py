"""Contrib layers (reference
`python/mxnet/gluon/contrib/nn/basic_layers.py`)."""
from __future__ import annotations

from ...block import Block, HybridBlock
from ...nn import (Sequential, HybridSequential, Embedding, BatchNorm,
                   SyncBatchNorm as _NnSyncBatchNorm)


class Concurrent(Sequential):
    """Run children on the same input, concat outputs
    (reference `basic_layers.py:Concurrent`)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from .... import ndarray as nd
        out = [block(x) for block in self._children.values()]
        return nd.concat(*out, dim=self.axis)


class HybridConcurrent(HybridSequential):
    """Hybridizable Concurrent (reference `basic_layers.py:46`)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)


class Identity(HybridBlock):
    """Pass-through (reference `basic_layers.py:Identity`) — the skip
    branch of a HybridConcurrent."""

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(Block):
    """API-compatible sparse-grad embedding (reference
    `basic_layers.py:SparseEmbedding`).

    Design note: on TPU the gradient of a gather is itself a fused XLA
    scatter-add — there is no sparse row_sparse gradient tensor to
    exploit, so this delegates to the dense Embedding (the sparse
    STORAGE path stays host-side per the framework's sparse stance)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        self._embed = Embedding(input_dim, output_dim, dtype=dtype,
                                weight_initializer=weight_initializer)
        self.register_child(self._embed)

    def forward(self, x):
        return self._embed(x)


class SyncBatchNorm(_NnSyncBatchNorm):
    """Kept at its historical contrib path; the implementation moved to
    `gluon.nn.SyncBatchNorm` (distributed BN with a psum of moments over
    the dp axis inside SPMD regions; global-batch statistics by
    construction under the fused train step)."""


class _PixelShuffle(HybridBlock):
    def __init__(self, factor, dims, **kwargs):
        super().__init__(**kwargs)
        self._factors = ((factor,) * dims if isinstance(factor, int)
                         else tuple(factor))
        assert len(self._factors) == dims

    def hybrid_forward(self, F, x):
        import numpy as _np
        # implemented with reshape+transpose over the channel dim
        # (reference contrib PixelShuffleND)
        f = self._factors
        if len(f) == 1:
            x = F.reshape(x, shape=(0, -4, -1, f[0], 0))     # (N,C,f,W)
            x = F.transpose(x, axes=(0, 1, 3, 2))
            return F.reshape(x, shape=(0, 0, -3))
        if len(f) == 2:
            x = F.reshape(x, shape=(0, -4, -1, f[0] * f[1], 0, 0))
            x = F.reshape(x, shape=(0, 0, -4, f[0], f[1], 0, 0))
            x = F.transpose(x, axes=(0, 1, 4, 2, 5, 3))
            return F.reshape(x, shape=(0, 0, -3, -3))
        # -4 splits one dim into two; chain three splits to factor the
        # channel dim into (C, f1, f2, f3)
        x = F.reshape(x, shape=(0, -4, -1, f[0] * f[1] * f[2], 0, 0, 0))
        x = F.reshape(x, shape=(0, 0, -4, f[0], f[1] * f[2], 0, 0, 0))
        x = F.reshape(x, shape=(0, 0, 0, -4, f[1], f[2], 0, 0, 0))
        x = F.transpose(x, axes=(0, 1, 5, 2, 6, 3, 7, 4))
        return F.reshape(x, shape=(0, 0, -3, -3, -3))


class PixelShuffle1D(_PixelShuffle):
    """(N, C*f, W) -> (N, C, W*f) (reference PixelShuffle1D)."""

    def __init__(self, factor, **kwargs):
        super().__init__(factor, 1, **kwargs)


class PixelShuffle2D(_PixelShuffle):
    """(N, C*f1*f2, H, W) -> (N, C, H*f1, W*f2)."""

    def __init__(self, factor, **kwargs):
        super().__init__(factor, 2, **kwargs)


class PixelShuffle3D(_PixelShuffle):
    """(N, C*f1*f2*f3, D, H, W) -> (N, C, D*f1, H*f2, W*f3)."""

    def __init__(self, factor, **kwargs):
        super().__init__(factor, 3, **kwargs)
