"""`gluon.contrib` (reference `python/mxnet/gluon/contrib/`)."""
from . import data, estimator, nn, rnn  # noqa: F401
from .estimator import Estimator  # noqa: F401
