"""Convolutional recurrent cells (reference
`python/mxnet/gluon/contrib/rnn/conv_rnn_cell.py`): gate pre-activations
are convolutions over spatial feature maps instead of dense projections —
the state h is (C_hidden, *spatial).  Each timestep is still one fused
XLA program on TPU; the convs land on the MXU."""
from __future__ import annotations

import numpy as np

from ....base import MXNetError
from ...rnn.rnn_cell import HybridRecurrentCell


def _conv_out_shape(in_shape, kernel, pad, dilate):
    return tuple(
        int(np.floor((s + 2 * p - d * (k - 1) - 1)) + 1)
        for s, k, p, d in zip(in_shape, kernel, pad, dilate))


class _BaseConvRNNCell(HybridRecurrentCell):
    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad, i2h_dilate, h2h_dilate, activation, prefix, params,
                 dims, n_gates):
        super().__init__(prefix=prefix, params=params)
        self._input_shape = tuple(input_shape)   # (C_in, *spatial)
        self._hidden_channels = hidden_channels
        self._activation = activation
        self._dims = dims
        self._n_gates = n_gates

        def _tup(v):
            return (v,) * dims if isinstance(v, int) else tuple(v)

        self._i2h_kernel = _tup(i2h_kernel)
        self._h2h_kernel = _tup(h2h_kernel)
        for k in self._h2h_kernel:
            if k % 2 == 0:
                raise MXNetError(
                    f"h2h_kernel must be odd so the state keeps its shape; "
                    f"got {self._h2h_kernel}")
        self._i2h_pad = _tup(i2h_pad)
        self._i2h_dilate = _tup(i2h_dilate)
        self._h2h_dilate = _tup(h2h_dilate)
        self._h2h_pad = tuple(d * (k - 1) // 2 for d, k in
                              zip(self._h2h_dilate, self._h2h_kernel))

        self._state_shape = (hidden_channels,) + _conv_out_shape(
            self._input_shape[1:], self._i2h_kernel, self._i2h_pad,
            self._i2h_dilate)

        g = n_gates
        self.i2h_weight = self.params.get(
            "i2h_weight",
            shape=(g * hidden_channels, self._input_shape[0])
            + self._i2h_kernel)
        self.h2h_weight = self.params.get(
            "h2h_weight",
            shape=(g * hidden_channels, hidden_channels) + self._h2h_kernel)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(g * hidden_channels,), init="zeros")
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(g * hidden_channels,), init="zeros")

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size,) + self._state_shape,
                 "__layout__": "NC" + "DHW"[3 - self._dims:]}] \
            * self._n_states

    def _conv_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                      i2h_bias, h2h_bias):
        g = self._n_gates
        i2h = F.Convolution(inputs, i2h_weight, i2h_bias,
                            kernel=self._i2h_kernel, pad=self._i2h_pad,
                            dilate=self._i2h_dilate,
                            num_filter=g * self._hidden_channels)
        h2h = F.Convolution(states[0], h2h_weight, h2h_bias,
                            kernel=self._h2h_kernel, pad=self._h2h_pad,
                            dilate=self._h2h_dilate,
                            num_filter=g * self._hidden_channels)
        return i2h, h2h


class _ConvRNNCell(_BaseConvRNNCell):
    _n_states = 1

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad, i2h_dilate, h2h_dilate, activation, prefix, params,
                 dims):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, i2h_pad, i2h_dilate, h2h_dilate,
                         activation, prefix, params, dims, n_gates=1)

    def _alias(self):
        return "conv_rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_forward(F, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        output = F.Activation(i2h + h2h, act_type=self._activation)
        return output, [output]


class _ConvLSTMCell(_BaseConvRNNCell):
    _n_states = 2

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad, i2h_dilate, h2h_dilate, activation, prefix, params,
                 dims):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, i2h_pad, i2h_dilate, h2h_dilate,
                         activation, prefix, params, dims, n_gates=4)

    def _alias(self):
        return "conv_lstm"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_forward(F, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        gates = i2h + h2h
        sl = F.SliceChannel(gates, num_outputs=4, axis=1)
        i = F.Activation(sl[0], act_type="sigmoid")
        f = F.Activation(sl[1], act_type="sigmoid")
        g = F.Activation(sl[2], act_type=self._activation)
        o = F.Activation(sl[3], act_type="sigmoid")
        next_c = f * states[1] + i * g
        next_h = o * F.Activation(next_c, act_type=self._activation)
        return next_h, [next_h, next_c]


class _ConvGRUCell(_BaseConvRNNCell):
    _n_states = 1

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad, i2h_dilate, h2h_dilate, activation, prefix, params,
                 dims):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, i2h_pad, i2h_dilate, h2h_dilate,
                         activation, prefix, params, dims, n_gates=3)

    def _alias(self):
        return "conv_gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_forward(F, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        i2h_s = F.SliceChannel(i2h, num_outputs=3, axis=1)
        h2h_s = F.SliceChannel(h2h, num_outputs=3, axis=1)
        reset = F.Activation(i2h_s[0] + h2h_s[0], act_type="sigmoid")
        update = F.Activation(i2h_s[1] + h2h_s[1], act_type="sigmoid")
        cand = F.Activation(i2h_s[2] + reset * h2h_s[2],
                            act_type=self._activation)
        next_h = update * states[0] + (1.0 - update) * cand
        return next_h, [next_h]


def _make(base, dims, default_act):
    class Cell(base):
        def __init__(self, input_shape, hidden_channels, i2h_kernel,
                     h2h_kernel, i2h_pad=0, i2h_dilate=1, h2h_dilate=1,
                     activation=default_act, prefix=None, params=None):
            super().__init__(input_shape, hidden_channels, i2h_kernel,
                             h2h_kernel, i2h_pad, i2h_dilate, h2h_dilate,
                             activation, prefix, params, dims)
    return Cell


Conv1DRNNCell = _make(_ConvRNNCell, 1, "tanh")
Conv2DRNNCell = _make(_ConvRNNCell, 2, "tanh")
Conv3DRNNCell = _make(_ConvRNNCell, 3, "tanh")
Conv1DLSTMCell = _make(_ConvLSTMCell, 1, "tanh")
Conv2DLSTMCell = _make(_ConvLSTMCell, 2, "tanh")
Conv3DLSTMCell = _make(_ConvLSTMCell, 3, "tanh")
Conv1DGRUCell = _make(_ConvGRUCell, 1, "tanh")
Conv2DGRUCell = _make(_ConvGRUCell, 2, "tanh")
Conv3DGRUCell = _make(_ConvGRUCell, 3, "tanh")
for _n, _c in list(globals().items()):
    if _n.startswith("Conv") and _n.endswith("Cell"):
        _c.__name__ = _n
        _c.__qualname__ = _n
