"""Contrib recurrent cells (reference
`python/mxnet/gluon/contrib/rnn/rnn_cell.py`)."""
from __future__ import annotations

from ...rnn.rnn_cell import HybridRecurrentCell, ModifierCell, \
    _format_sequence


class VariationalDropoutCell(ModifierCell):
    """Variational (locked) dropout: ONE mask per sequence for inputs,
    states and outputs (Gal & Ghahramani; reference
    `contrib/rnn/rnn_cell.py:VariationalDropoutCell`)."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0):
        super().__init__(base_cell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    def _alias(self):
        return "vardrop"

    def reset(self):
        super().reset()
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    @staticmethod
    def _mask(F, like, p):
        # Dropout of ones IS the inverted-dropout mask {0, 1/(1-p)}:
        # drawing it once and multiplying each step keeps expectation 1
        return F.Dropout(F.ones_like(like), p=p)

    def __call__(self, inputs, states):
        from .... import ndarray as nd_mod
        from ....ndarray.ndarray import NDArray
        F = nd_mod if isinstance(inputs, NDArray) else None
        if F is None:
            from .... import symbol as F
        if self.drop_inputs:
            if self._input_mask is None:
                self._input_mask = self._mask(F, inputs, self.drop_inputs)
            inputs = inputs * self._input_mask
        if self.drop_states:
            if self._state_mask is None:
                self._state_mask = self._mask(F, states[0],
                                              self.drop_states)
            states = [states[0] * self._state_mask] + list(states[1:])
        output, states = self.base_cell(inputs, states)
        if self.drop_outputs:
            if self._output_mask is None:
                self._output_mask = self._mask(F, output, self.drop_outputs)
            output = output * self._output_mask
        return output, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        return super().unroll(length, inputs, begin_state, layout,
                              merge_outputs, valid_length)


class LSTMPCell(HybridRecurrentCell):
    """LSTM with a hidden-state projection (LSTMP, Sak et al. 2014;
    reference `contrib/rnn/rnn_cell.py:LSTMPCell`): the recurrent/output
    path runs through h = W_p c_out, shrinking the recurrent matmul —
    on TPU this keeps the per-step MXU tiles dense for large cells."""

    def __init__(self, hidden_size, projection_size,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 h2r_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * hidden_size, projection_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.h2r_weight = self.params.get(
            "h2r_weight", shape=(projection_size, hidden_size),
            init=h2r_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._projection_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "lstmp"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       h2r_weight, i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        sl = F.SliceChannel(gates, num_outputs=4, axis=1)
        i = F.Activation(sl[0], act_type="sigmoid")
        f = F.Activation(sl[1], act_type="sigmoid")
        g = F.Activation(sl[2], act_type="tanh")
        o = F.Activation(sl[3], act_type="sigmoid")
        next_c = f * states[1] + i * g
        hidden = o * F.Activation(next_c, act_type="tanh")
        next_r = F.FullyConnected(hidden, h2r_weight, no_bias=True,
                                  num_hidden=self._projection_size)
        return next_r, [next_r, next_c]
