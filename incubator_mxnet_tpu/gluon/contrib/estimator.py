"""Minimal training-loop helper in the spirit of gluon.contrib."""
from __future__ import annotations


class Estimator:
    """Simple fit loop over a Gluon net + loss + trainer."""

    def __init__(self, net, loss, trainer, metrics=None, context=None):
        self.net = net
        self.loss = loss
        self.trainer = trainer
        self.metrics = metrics or []
        self.context = context

    def fit(self, train_data, epochs=1):
        from ... import autograd
        for _ in range(epochs):
            for batch in train_data:
                data, label = batch
                with autograd.record():
                    out = self.net(data)
                    loss = self.loss(out, label)
                loss.backward()
                self.trainer.step(data.shape[0])
        return self
