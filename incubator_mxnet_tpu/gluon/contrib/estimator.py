"""Estimator: event-driven gluon training loop (reference
`python/mxnet/gluon/contrib/estimator/estimator.py` + event_handler.py).

The loop itself stays thin — forward/backward/step per batch — and every
cross-cutting concern (logging, metric bookkeeping, checkpointing, early
stopping) is an EventHandler hooked on train_begin/epoch_begin/
batch_begin/batch_end/epoch_end/train_end, exactly the reference's
architecture.
"""
from __future__ import annotations

import logging
import time

from ...base import MXNetError

__all__ = ["Estimator", "EventHandler", "LoggingHandler",
           "CheckpointHandler", "EarlyStoppingHandler", "StopTraining"]


class StopTraining(Exception):
    """Raised by handlers (early stopping) to end fit() cleanly."""


class EventHandler:
    def train_begin(self, estimator):
        pass

    def epoch_begin(self, estimator):
        pass

    def batch_begin(self, estimator):
        pass

    def batch_end(self, estimator):
        pass

    def epoch_end(self, estimator):
        pass

    def train_end(self, estimator):
        pass


def _metric_items(metric):
    names, vals = metric.get()
    if not isinstance(names, list):
        names, vals = [names], [vals]
    return list(zip(names, vals))


class LoggingHandler(EventHandler):
    """Per-epoch (and optionally per-N-batches) metric logging
    (reference `event_handler.py:LoggingHandler`)."""

    def __init__(self, log_interval="epoch", logger=None):
        self.log_interval = log_interval
        self.logger = logger or logging.getLogger("Estimator")

    def train_begin(self, est):
        self._t0 = time.time()

    def batch_end(self, est):
        if self.log_interval == "epoch" or \
                est.batch_idx % self.log_interval:
            return
        msg = " ".join(f"{n}={v:.6f}" for m in est.train_metrics
                       for n, v in _metric_items(m))
        self.logger.info("[epoch %d][batch %d] %s", est.epoch,
                         est.batch_idx, msg)

    def epoch_end(self, est):
        parts = [f"train_{n}={v:.6f}" for m in est.train_metrics
                 for n, v in _metric_items(m)]
        parts += [f"val_{n}={v:.6f}" for m in est.val_metrics
                  for n, v in _metric_items(m)]
        self.logger.info("[epoch %d] %s time=%.1fs", est.epoch,
                         " ".join(parts), time.time() - self._t0)


class CheckpointHandler(EventHandler):
    """Save parameters each epoch; keep the best by a monitored metric
    (reference `event_handler.py:CheckpointHandler`).

    Parameters only — for preemption-safe training (async full-state
    snapshots, atomic manifests, mid-epoch auto-resume) use
    `incubator_mxnet_tpu.checkpoint.ElasticCheckpointHandler`."""

    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 mode="min", save_best=False):
        import os
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.save_best = save_best
        self.best = float("inf") if mode == "min" else -float("inf")
        self.mode = mode
        os.makedirs(model_dir, exist_ok=True)

    def epoch_end(self, est):
        import os
        path = os.path.join(self.model_dir,
                            f"{self.model_prefix}-epoch{est.epoch}.params")
        est.net.save_parameters(path)
        if self.save_best and self.monitor is not None:
            val = _metric_value(est, self.monitor)
            better = val < self.best if self.mode == "min" else \
                val > self.best
            if better:
                self.best = val
                est.net.save_parameters(os.path.join(
                    self.model_dir, f"{self.model_prefix}-best.params"))


class EarlyStoppingHandler(EventHandler):
    """Stop when the monitored metric stops improving (reference
    `event_handler.py:EarlyStoppingHandler`)."""

    def __init__(self, monitor, mode="min", patience=3, min_delta=0.0):
        self.monitor = monitor
        self.mode = mode
        self.patience = patience
        self.min_delta = min_delta
        self.best = float("inf") if mode == "min" else -float("inf")
        self.waited = 0

    def epoch_end(self, est):
        val = _metric_value(est, self.monitor)
        improved = (val < self.best - self.min_delta if self.mode == "min"
                    else val > self.best + self.min_delta)
        if improved:
            self.best = val
            self.waited = 0
        else:
            self.waited += 1
            if self.waited >= self.patience:
                raise StopTraining(
                    f"early stop: {self.monitor} plateaued at {self.best}")


def _metric_value(est, name):
    # prefer validation, but a never-updated val metric (no val_data)
    # reports nan and must not shadow the train metric of the same name
    candidates = []
    for m in list(est.val_metrics) + list(est.train_metrics):
        for n, v in _metric_items(m):
            if n == name:
                candidates.append(v)
    for v in candidates:
        if v == v:                       # not nan
            return v
    if candidates:
        return candidates[0]
    raise MXNetError(f"EarlyStopping/Checkpoint: metric {name!r} not found")


class Estimator:
    """Reference `estimator.py:Estimator` — fit with event handlers."""

    def __init__(self, net, loss, train_metrics=None, trainer=None,
                 context=None):
        from ... import metric as metric_mod
        self.net = net
        self.loss = loss
        metrics = train_metrics if train_metrics is not None \
            else [metric_mod.Accuracy()]
        if not isinstance(metrics, (list, tuple)):
            metrics = [metrics]
        self.train_metrics = list(metrics)
        import copy
        self.val_metrics = [copy.deepcopy(m) for m in self.train_metrics]
        for m in self.val_metrics:
            m.reset()
        self.trainer = trainer
        self.context = context
        self.epoch = 0
        self.batch_idx = 0
        self._epochs_done = 0
        self._resume_batches = 0  # set by checkpoint.ElasticCheckpointHandler

    def _ctx(self):
        if self.context is not None:
            return self.context
        try:
            return next(iter(self.net.collect_params().values())) \
                .list_ctx()[0]
        except Exception:
            return None

    def _place(self, data, label):
        """Batches land on the net's context (the reference estimator's
        split_and_load step, single-device form)."""
        ctx = self._ctx()
        if ctx is not None:
            if hasattr(data, "as_in_context"):
                data = data.as_in_context(ctx)
            if hasattr(label, "as_in_context"):
                label = label.as_in_context(ctx)
        return data, label

    def evaluate(self, val_data):
        for m in self.val_metrics:
            m.reset()
        for data, label in val_data:
            data, label = self._place(data, label)
            out = self.net(data)
            for m in self.val_metrics:
                m.update([label], [out])
        return self.val_metrics

    def fit(self, train_data, val_data=None, epochs=1, event_handlers=None):
        from ... import autograd
        import os as _os
        if self.trainer is None:
            from ..trainer import Trainer
            self.trainer = Trainer(self.net.collect_params(), "sgd",
                                   {"learning_rate": 0.01})
        # TPU fast path: the whole train step (forward + loss + backward +
        # optimizer + aux + metric) as ONE donated XLA program per input
        # signature (gluon/fused_step.py), with transparent fallback to
        # the reference eager loop below
        from ... import config as _config
        fused = getattr(self, "_fused", None)
        if fused is not None and (
                fused._trainer is not self.trainer or
                fused._loss_fn is not self.loss or
                fused._metrics != list(self.train_metrics)):
            fused = self._fused = None   # trainer/loss/metrics replaced
        if not _config.get("MXNET_FUSED_TRAIN_STEP"):
            fused = None
        elif fused is None:
            from ..fused_step import GluonFusedStep
            fused = self._fused = GluonFusedStep.try_build(
                self.net, self.loss, self.trainer, self.train_metrics)
        # h2d staging ring (io_plane.py, MXNET_IO_RING): wrap the
        # training loader so (data, label) pairs transfer on the
        # mx-io-h2d thread with device-resident prefetch — the fused
        # Gluon step's device_put then adopts already-placed buffers
        # and the Trainer never blocks on a transfer
        io_loader = None
        if fused is not None and _config.get("MXNET_IO_RING"):
            from ... import io_plane as _io_plane
            ctx = self._ctx()
            if ctx is not None and \
                    not isinstance(train_data,
                                   _io_plane.DevicePrefetchLoader):
                try:
                    train_data = io_loader = \
                        _io_plane.DevicePrefetchLoader(train_data, ctx=ctx)
                except Exception:
                    io_loader = None
        handlers = list(event_handlers or [LoggingHandler()])
        # block mode: K batches per dispatch as ONE lax.scan program
        # (gluon/fused_step.py call_block) — handlers still fire per batch,
        # in bursts of K after each block.  Matches Module.fit's blocks.
        block_k = max(int(_config.get("MXNET_FUSED_STEP_BLOCK")), 1) \
            if fused is not None else 1
        try:
            for h in handlers:
                h.train_begin(self)
            end_epoch = self._epochs_done + epochs
            if getattr(self, "_resume_total_epochs", False):
                # a checkpoint-resumed run relaunches the SAME command:
                # `epochs` is the total budget, not extra epochs on top of
                # the restored position (ElasticCheckpointHandler sets this)
                self._resume_total_epochs = False
                end_epoch = max(epochs, self._epochs_done)
            for self.epoch in range(self._epochs_done, end_epoch):
                for m in self.train_metrics:
                    m.reset()
                for h in handlers:
                    h.epoch_begin(self)
                self.batch_idx = 0
                data_iter = iter(train_data)
                # mid-epoch resume (checkpoint.ElasticCheckpointHandler
                # sets _resume_batches in train_begin): fast-forward the
                # already-trained batches of the first resumed epoch
                skip = int(getattr(self, "_resume_batches", 0) or 0)
                if skip:
                    self._resume_batches = 0
                    for _ in range(skip):
                        try:
                            next(data_iter)
                        except StopIteration:
                            break
                    self.batch_idx = skip
                # batches whose updates have fully LANDED in the params —
                # in fused block mode this leads batch_idx during the
                # post-block handler burst (the whole block applied before
                # its K batch_end events fire); checkpoint handlers must
                # record THIS as the resume position, not batch_idx
                self._applied_batches = self.batch_idx
                exhausted = False
                while not exhausted:
                    block = []
                    want = block_k if (fused is not None and
                                       not fused.broken) else 1
                    while len(block) < want:
                        try:
                            block.append(next(data_iter))
                        except StopIteration:
                            exhausted = True
                            break
                    if not block:
                        break
                    block = [self._place(d, l) for d, l in block]
                    if len(block) == want and want > 1 and \
                            fused.call_block(block, block[0][0].shape[0]):
                        self._applied_batches = self.batch_idx + len(block)
                        for _bi, _dl in enumerate(block):
                            # batch-_bi handlers observe batch-_bi metric
                            # state (per-logical-step semantics), not the
                            # block-final totals — exposed before
                            # batch_begin so no handler sees the future
                            fused.set_block_cursor(_bi)
                            for h in handlers:
                                h.batch_begin(self)
                            for h in handlers:
                                h.batch_end(self)
                            self.batch_idx += 1
                        continue
                    # per-batch fallback (also how deferred-init params
                    # materialize: the first eager forward fixes shapes,
                    # after which the NEXT block fuses)
                    for data, label in block:
                        for h in handlers:
                            h.batch_begin(self)
                        if fused is not None and not fused.broken and \
                                fused(data, label, data.shape[0]):
                            self._applied_batches = self.batch_idx + 1
                            for h in handlers:
                                h.batch_end(self)
                            self.batch_idx += 1
                            continue
                        with autograd.record():
                            out = self.net(data)
                            loss = self.loss(out, label)
                        loss.backward()
                        self.trainer.step(data.shape[0])
                        self._applied_batches = self.batch_idx + 1
                        for m in self.train_metrics:
                            m.update([label], [out])
                        for h in handlers:
                            h.batch_end(self)
                        self.batch_idx += 1
                if val_data is not None:
                    self.evaluate(val_data)
                self._epochs_done = self.epoch + 1
                for h in handlers:
                    h.epoch_end(self)
        except StopTraining as e:
            logging.getLogger("Estimator").info(str(e))
        finally:
            if io_loader is not None:
                io_loader.close()
        for h in handlers:
            h.train_end(self)
        return self
