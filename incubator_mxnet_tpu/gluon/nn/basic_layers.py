"""Basic neural network layers
(reference `python/mxnet/gluon/nn/basic_layers.py` — Dense:142, BatchNorm:273,
Embedding:369, LayerNorm:532, Sequential, Dropout, Flatten, Lambda)."""
from __future__ import annotations

from ..block import Block, HybridBlock
from ...base import MXNetError

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "Embedding",
           "BatchNorm", "SyncBatchNorm", "InstanceNorm", "LayerNorm",
           "Flatten", "Lambda", "HybridLambda"]


class Sequential(Block):
    """Stack of Blocks (reference `basic_layers.py:Sequential`)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())


def _scan_child_sig(block):
    """Structural identity of a child for scan-over-layers grouping:
    class, scalar config attributes, the (suffix, shape, dtype,
    grad_req) list of every owned parameter (CURRENT shapes — deferred
    init already resolved inside a fused trace), recursively over
    children.  Two children with equal signatures run the same math
    modulo their parameter values, so a run of them can lower to one
    `lax.scan` body over stacked per-layer params."""
    cfg = []
    for k, v in vars(block).items():
        if k in ("_prefix", "_name"):
            continue
        if isinstance(v, (bool, int, float, str, type(None))):
            cfg.append((k, v))
        elif isinstance(v, tuple) and all(
                isinstance(e, (bool, int, float, str)) for e in v):
            cfg.append((k, v))
        elif isinstance(v, dict) and all(
                isinstance(e, (bool, int, float, str, type(None)))
                for e in v.values()):
            cfg.append((k, tuple(sorted(
                (str(a), b) for a, b in v.items()))))
    plist = []
    for suffix, p in block._collect_params_with_prefix().items():
        d = p.data()
        plist.append((suffix, tuple(d.shape), str(d.dtype),
                      p.grad_req))
    return (type(block).__name__,
            tuple(sorted(cfg, key=lambda t: t[0])),
            tuple(plist),
            tuple(_scan_child_sig(c) for c in block._children.values()))


class HybridSequential(HybridBlock):
    """Hybridizable stack (reference `basic_layers.py:HybridSequential`).

    Inside a fused-step trace with MXNET_FUSED_SCAN armed
    (`gluon.fused_step.scan_lowering_active`), runs of >= 2 structurally
    identical children (`_scan_child_sig`) evaluate as ONE `lax.scan`
    body over their stacked parameters instead of N inlined copies —
    the graph handed to XLA carries one layer body, shrinking compile
    time for deep repeated stacks.  Bit-parity with the plain loop:
    stacking is lossless, the body is the same child math, and any
    failure falls back to inlining that run."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        blocks = list(self._children.values())
        from ...ndarray.ndarray import NDArray
        if len(blocks) >= 2 and isinstance(x, NDArray):
            from ..fused_step import scan_lowering_active
            if scan_lowering_active():
                try:
                    sigs = [_scan_child_sig(b) for b in blocks]
                except Exception:
                    sigs = None
                if sigs is not None:
                    return self._scan_forward(blocks, sigs, x)
        for block in blocks:
            x = block(x)
        return x

    def _scan_forward(self, blocks, sigs, x):
        """The plain child loop with every maximal run of >= 2 equal-
        signature children collapsed into one `lax.scan` (per-run
        fallback to inlining on any lowering failure)."""
        i = 0
        while i < len(blocks):
            j = i + 1
            while j < len(blocks) and sigs[j] == sigs[i]:
                j += 1
            if j - i >= 2:
                try:
                    x = self._scan_run(blocks[i:j], x)
                    i = j
                    continue
                except Exception:
                    pass   # inline this run (dead stack eqns are DCE'd)
            x = blocks[i](x)
            i += 1
        return x

    def _scan_run(self, blocks, x):
        """Evaluate a run of structurally identical children as one
        `lax.scan`: per-layer params stack as scan xs, the template
        (first) child runs the body with its Parameters swapped to the
        per-layer slices, and aux-state updates (BN running stats, in-
        place on the body shells) come back as scan ys, written back to
        each layer's parameter storage after the scan."""
        import jax
        import jax.numpy as jnp
        from ...ndarray.ndarray import NDArray
        from ..fused_step import _SwapParams

        template = blocks[0]
        plists = [list(b._collect_params_with_prefix().values())
                  for b in blocks]
        tparams = plists[0]
        aux_slots = [s for s, p in enumerate(tparams)
                     if p.grad_req in (None, "null")]
        stacks = tuple(
            jnp.stack([pl[s].data()._data for pl in plists])
            for s in range(len(tparams)))
        ctx = x.context
        x_in = x._data

        def body(c, row):
            shells = [NDArray(v, ctx=ctx) for v in row]
            with _SwapParams(tparams, shells):
                out = template(NDArray(c, ctx=ctx))
            aux_out = tuple(shells[s]._data for s in aux_slots)
            return out._data, aux_out

        c_out, ys = jax.lax.scan(body, x_in, stacks)
        # aux updates land back on each layer's CURRENT storage (the
        # outer trace's shells) so the fused core gathers them exactly
        # as the inlined path would
        for slot_j, s in enumerate(aux_slots):
            for layer, pl in enumerate(plists):
                pl[s].data()._data = ys[slot_j][layer]
        return NDArray(c_out, ctx=ctx)

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer (reference `basic_layers.py:142 Dense`)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self._units = units
            self._in_units = in_units
            self._flatten = flatten
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=bias_initializer, allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def hybrid_forward(self, F, x, weight, bias=None):
        if bias is None:
            out = F.FullyConnected(x, weight, num_hidden=self._units,
                                   no_bias=True, flatten=self._flatten,
                                   name="fwd")
        else:
            out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                                   flatten=self._flatten, name="fwd")
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        shape = self.weight.shape
        return f"Dense({shape[1] if shape[1] else None} -> {shape[0]}, " \
               f"linear)"


class Dropout(HybridBlock):
    """Reference `basic_layers.py:Dropout`."""

    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        return F.Dropout(x, p=self._rate, axes=self._axes, name="fwd")

    def __repr__(self):
        return f"Dropout(p = {self._rate}, axes={self._axes})"


class Embedding(HybridBlock):
    """Reference `basic_layers.py:369 Embedding`."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim,
                        "dtype": dtype, "sparse_grad": sparse_grad}
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, name="fwd", **self._kwargs)

    def __repr__(self):
        return f"Embedding({self._input_dim} -> {self._output_dim}, " \
               f"{self._kwargs['dtype']})"


class BatchNorm(HybridBlock):
    """Reference `basic_layers.py:273 BatchNorm`."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"axis": axis, "eps": epsilon, "momentum": momentum,
                        "fix_gamma": not scale,
                        "use_global_stats": use_global_stats}
        self._axis = axis
        if in_channels != 0:
            self.in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)
            self.running_mean = self.params.get(
                "running_mean", grad_req="null", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                "running_var", grad_req="null", shape=(in_channels,),
                init=running_variance_initializer, allow_deferred_init=True,
                differentiable=False)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        return F.BatchNorm(x, gamma, beta, running_mean, running_var,
                           name="fwd", **self._kwargs)

    def __repr__(self):
        in_channels = self.gamma.shape[0]
        return f"BatchNorm(axis={self._axis}, in_channels={in_channels})"


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm: statistics over the GLOBAL batch
    (reference `contrib/nn/basic_layers.py:SyncBatchNorm` /
    `sync_batch_norm-inl.h`, promoted into `gluon.nn` per the
    MLPerf-pods distributed-BN recipe).

    Sets ``sync=True`` on the underlying BatchNorm op: inside an
    explicit SPMD region (`shard_map` with the dp axis bound —
    `parallel.data_parallel_step`, `zero_train_step`) the moments psum
    over ``sync_axis``; under the fused `Module.fit` train step the
    program is global-view, so batch statistics are already global and
    this layer is numerically identical to `BatchNorm` there (the
    stronger semantics by construction).  ``num_devices`` is accepted
    for reference API compatibility; the axis size comes from the mesh.
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, sync_axis="dp", **kwargs):
        super().__init__(momentum=momentum, epsilon=epsilon,
                         in_channels=in_channels, **kwargs)
        self._kwargs["sync"] = True
        self._kwargs["sync_axis"] = sync_axis
        self._num_devices = num_devices


class InstanceNorm(HybridBlock):
    """Reference `basic_layers.py:InstanceNorm`."""

    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"eps": epsilon}
        self._axis = axis
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def hybrid_forward(self, F, x, gamma, beta):
        if self._axis == 1:
            return F.InstanceNorm(x, gamma, beta, name="fwd", **self._kwargs)
        x = x.swapaxes(1, self._axis) if hasattr(x, "swapaxes") else \
            F.swapaxes(x, dim1=1, dim2=self._axis)
        out = F.InstanceNorm(x, gamma, beta, name="fwd", **self._kwargs)
        return F.swapaxes(out, dim1=1, dim2=self._axis)


class LayerNorm(HybridBlock):
    """Reference `basic_layers.py:532 LayerNorm`."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._kwargs = {"eps": epsilon, "axis": axis}
        self._axis = axis
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, name="fwd", **self._kwargs)


class Flatten(HybridBlock):
    """Reference `basic_layers.py:Flatten`."""

    def hybrid_forward(self, F, x):
        return F.Flatten(x)

    def __repr__(self):
        return "Flatten"


class Lambda(Block):
    """Wrap a function as a Block (reference `basic_layers.py:Lambda`)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd_mod
            assert hasattr(nd_mod, function), \
                f"Function name {function} is not found in ndarray."
            self._func_impl = getattr(nd_mod, function)
        elif callable(function):
            self._func_impl = function
        else:
            raise ValueError("Unrecognized function in lambda: "
                             f"{function} of type {type(function)}")
        self._func_name = getattr(self._func_impl, "__name__", "custom")

    def forward(self, *args):
        return self._func_impl(*args)

    def __repr__(self):
        return f"Lambda({self._func_name})"


class HybridLambda(HybridBlock):
    """Reference `basic_layers.py:HybridLambda`."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd_mod
            from ... import symbol as sym_mod
            assert hasattr(nd_mod, function) and hasattr(sym_mod, function), \
                f"Function name {function} is not found in ndarray/symbol."
            self._func = lambda F, *args: getattr(F, function)(*args)
            self._func_name = function
        elif callable(function):
            self._func = function
            self._func_name = getattr(function, "__name__", "custom")
        else:
            raise ValueError("Unrecognized function in lambda")

    def hybrid_forward(self, F, x, *args):
        return self._func(F, x, *args)

    def __repr__(self):
        return f"HybridLambda({self._func_name})"


from .activations import Activation  # noqa: E402  (circular-free tail import)
