"""`gluon.nn` neural-network layers (reference `python/mxnet/gluon/nn/`)."""
from .activations import *
from .basic_layers import *
from .conv_layers import *
from .sparse import *
from .activations import Activation
