"""Gluon block over the sharded sparse-embedding tier (mxembed).

`nn.Embedding` holds its table as a dense Parameter — fine until the
table outgrows one device's HBM.  `SparseEmbedding` instead wraps a
`embedding.ShardedEmbedding`: the forward pass looks rows up through
the device-resident hot-row cache (a data-plane fetch, not a Parameter
read), the looked-up block is an autograd LEAF, and after ``backward()``
the leaf's gradient is pushed row-sparse to the owning parameter-server
shards where the lazy optimizer applies it.  The dense parameters of
the surrounding net keep training through `Trainer` untouched.
"""
from __future__ import annotations

import numpy as np

from ..block import Block
from ...ndarray.ndarray import NDArray

__all__ = ["SparseEmbedding"]


class SparseEmbedding(Block):
    """Embedding lookup backed by a `ShardedEmbedding` table.

    ::

        table = embedding.ShardedEmbedding("user", rows, dim, servers,
                                           optimizer=opt)
        emb = nn.SparseEmbedding(table)
        with autograd.record():
            y = net(emb(ids), dense_x)
            L = loss(y, label)
        L.backward()
        emb.push_grads()        # row-sparse push, shard-side update
        trainer.step(batch)     # dense params as usual
    """

    def __init__(self, table, **kwargs):
        super().__init__(**kwargs)
        self._table = table
        self._pending = []      # (ids, leaf) since the last push

    @property
    def table(self):
        return self._table

    def forward(self, x):
        ids = np.asarray(
            x.asnumpy() if hasattr(x, "asnumpy") else x).astype(np.int64)
        flat = self._table.lookup(ids)      # device array, cache-hot
        out = NDArray(flat.reshape(ids.shape + (self._table.dim,)))
        # the lookup result is a leaf: backward leaves d(loss)/d(rows)
        # in out.grad, which push_grads ships row-sparse to the shards
        out.attach_grad()
        self._pending.append((ids, out))
        return out

    def push_grads(self):
        """Push every recorded lookup's gradient to the owning shards
        (duplicate ids pre-summed; lazy update applied server-side)."""
        pending, self._pending = self._pending, []
        for ids, leaf in pending:
            g = leaf.grad
            if g is None:
                continue
            self._table.push_grad(
                ids.ravel(),
                g.asnumpy().reshape(ids.size, self._table.dim))

    def __repr__(self):
        t = self._table
        return f"SparseEmbedding({t.num_rows} -> {t.dim}, " \
               f"{t.num_shards} shards, {t.partition})"
