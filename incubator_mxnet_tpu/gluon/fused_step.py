"""The Gluon training step as ONE donated XLA program.

`Module.fit` got its single-program hot loop in `fused.FusedTrainStep`;
this is the same treatment for the Gluon side, reachable from the public
`gluon.contrib.estimator.Estimator.fit` loop (the reference's Estimator,
`python/mxnet/gluon/contrib/estimator/estimator.py`).  The eager pattern

    with autograd.record():
        out = net(data); loss = loss_fn(out, label)
    loss.backward(); trainer.step(batch)

costs a dispatch for the CachedOp forward, one for the (fused) tape
backward — which must RECOMPUTE the forward for its residuals — and one
for the optimizer apply.  Here the whole thing traces once per input
signature into a single program: the net and loss blocks run their nd ops
on traced shells (every registered op is jax-traceable), `jax.vjp` takes
the gradients with the forward residuals shared (no recompute), the
PUBLIC optimizer applies via `fused._apply_traced`, BatchNorm aux states
and the metric accumulate in-graph, and every persistent buffer is a
donated carry.

Block mode (`call_block`, driven by Estimator.fit +
MXNET_FUSED_STEP_BLOCK): K batches run as ONE `lax.scan` program per
dispatch, amortizing host dispatch and write-back Python across K steps —
the Gluon analogue of `fused.FusedTrainStep`'s scan blocks.  The
framework trace runs once into a closed jaxpr shared by the 1-step and
every K-step program.

Eligibility (checked at build, with transparent fallback to the eager
loop): single-context trainer, no ZeRO/TP sharding, no RNG-consuming ops
(dropout nets fall back), metrics with `device_update`.
"""
from __future__ import annotations

import logging
import threading as _threading

import numpy as _np

from ..ndarray.ndarray import NDArray
from .. import autograd as _autograd
from ..fused import (_apply_traced, _no_rng, _state_data,
                     _state_write_back, _raise_if_unrecoverable,
                     _TracedCore, _one_step_jit, _scan_block_jit,
                     _BlockMetricView)

__all__ = ["GluonFusedStep"]

_log = logging.getLogger(__name__)


class _SwapParams:
    """Temporarily repoint Parameters' storage at traced shells."""

    def __init__(self, params, shells):
        self._params = params
        self._shells = shells
        self._saved = None

    def __enter__(self):
        self._saved = [p._data for p in self._params]
        for p, s in zip(self._params, self._shells):
            p._data = [s]

    def __exit__(self, *exc):
        for p, d in zip(self._params, self._saved):
            p._data = d


_SCAN_TRACE = _threading.local()


class _ScanLowering:
    """Arms scan-over-layers for the duration of the fused core's
    forward trace (MXNET_FUSED_SCAN): `HybridSequential` lowers runs of
    structurally identical children to ONE `lax.scan` body over stacked
    per-layer parameters instead of N inlined copies, so XLA compiles
    the layer body once.  Scoped to the trace — eager user forwards
    never pay the detection walk."""

    def __enter__(self):
        from .. import config as _cfg
        self._on = bool(_cfg.get("MXNET_FUSED_SCAN"))
        if self._on:
            _SCAN_TRACE.depth = getattr(_SCAN_TRACE, "depth", 0) + 1
        return self

    def __exit__(self, *exc):
        if self._on:
            _SCAN_TRACE.depth -= 1


def scan_lowering_active():
    """True while a fused gluon core trace wants scan-over-layers
    (checked by `HybridSequential.hybrid_forward`)."""
    return getattr(_SCAN_TRACE, "depth", 0) > 0


class GluonFusedStep:
    """One donated program for Estimator's train step (K per dispatch in
    block mode)."""

    @classmethod
    def try_build(cls, net, loss_fn, trainer, metrics):
        """Returns an instance or None when the configuration cannot fuse
        (the caller keeps the reference eager loop)."""
        try:
            if trainer is None or len(trainer._contexts) != 1:
                return None
            if getattr(trainer, "_zero", None) is not None:
                return None
            # every net parameter must be trainer-owned: anything outside
            # trainer._params would trace as a CONSTANT, silently ignoring
            # later set_data/load_parameters on e.g. frozen layers
            owned = {p.name for p in trainer._params}
            net_params = set(net.collect_params().keys()) \
                if hasattr(net, "collect_params") else owned
            if not net_params <= owned:
                return None
            for m in metrics:
                if getattr(m, "device_update", None) is None:
                    return None
            return cls(net, loss_fn, trainer, metrics)
        except Exception as e:
            _log.warning("gluon fused step unavailable (%s)", str(e)[:200])
            return None

    def __init__(self, net, loss_fn, trainer, metrics):
        self._net = net
        self._loss_fn = loss_fn
        self._trainer = trainer
        self._metrics = list(metrics)
        self._ctx = trainer._contexts[0]
        all_params = list(trainer._params)
        self._train_params = [p for p in all_params
                              if p.grad_req not in (None, "null")]
        self._aux_params = [p for p in all_params
                            if p.grad_req in (None, "null")]
        self._indices = [trainer._param2idx[p.name]
                         for p in self._train_params]
        self._opt = trainer._optimizer
        self._updater = trainer._updaters[0]
        self._jit = None
        self._jit_block = {}
        self._core_closed = None
        self._core_sig = None     # input signature the core was traced for
        self._core_cache = {}     # in_sig -> traced program set
        self.broken = False
        self._carry = None
        self._t_vec = None
        self._block_view = None   # per-step metric exposure for bursts
        self.last_loss = None
        self.last_outputs = None
        GluonFusedStep._seq = getattr(GluonFusedStep, "_seq", 0) + 1
        self._audit_key = f"GluonFusedStep#{GluonFusedStep._seq}"
        self._step_no = 0   # donation-tracker step counter

    def _donation_groups(self, ws, ss, auxs):
        """(owner_name, pytree) pairs for the donated carries — naming
        source for the donation tracker and unrecoverable errors."""
        groups = [(p.name, w) for p, w in zip(self._train_params, ws)]
        groups += [(p.name + ".state", s)
                   for p, s in zip(self._train_params, ss)]
        groups += [(p.name, a) for p, a in zip(self._aux_params, auxs)]
        return groups

    # -- build ---------------------------------------------------------------
    def _build_core(self):
        """The one-step train function over raw arrays; traced exactly once
        under `make_jaxpr` (the trace runs the whole net's Python)."""
        import jax
        import jax.numpy as jnp

        net, loss_fn = self._net, self._loss_fn
        tparams, aparams = self._train_params, self._aux_params
        metrics = self._metrics
        opt, indices, ctx = self._opt, self._indices, self._ctx

        def core(inner, x, rescale):
            ws, auxs, ss, mcarry, t_vec = inner
            data, label, lr_vec, wd_vec = x
            t_vec = t_vec + jnp.float32(1.0)

            def forward(pws):
                shells = [NDArray(w, ctx=ctx) for w in pws]
                aux_shells = [NDArray(a, ctx=ctx) for a in auxs]
                with _SwapParams(tparams, shells), \
                        _SwapParams(aparams, aux_shells), \
                        _autograd.pause(train_mode=True):
                    with _ScanLowering():
                        out = net(NDArray(data, ctx=ctx))
                    losses = loss_fn(out, NDArray(label, ctx=ctx))
                # BatchNorm-style aux updates landed in-place on the shells
                new_aux = tuple(s._data for s in aux_shells)
                return jnp.sum(losses._data), (out._data, losses._data,
                                               new_aux)

            loss_sum, vjp, (out, losses, new_aux) = \
                jax.vjp(forward, list(ws), has_aux=True)
            # scan carries must keep invariant dtypes: a bf16-cast net's
            # BN aux update may compute fp32 running stats — land them
            # back in the stored aux dtype (the 1-step jit tolerated the
            # widening; lax.scan correctly refuses)
            new_aux = tuple(
                na.astype(a.dtype) if na.dtype != a.dtype else na
                for na, a in zip(new_aux, auxs))
            (grads,) = vjp(jnp.ones((), loss_sum.dtype))
            new_ws, new_ss = _apply_traced(opt, indices, ws, grads, ss, ctx,
                                           lr_vec, wd_vec, t_vec, rescale)
            new_mcarry = []
            for m, (msum, mnum) in zip(metrics, mcarry):
                dsum, dnum = m.device_update([label], [out])
                new_mcarry.append((msum + jnp.asarray(dsum, jnp.float32),
                                   mnum + jnp.asarray(dnum, jnp.int32)))
            mean_loss = loss_sum / losses.size
            new_inner = (tuple(new_ws), tuple(new_aux), tuple(new_ss),
                         tuple(new_mcarry), t_vec)
            return new_inner, (mean_loss, out)

        return core

    def _trace_core(self, core, example):
        """Run the net's framework trace ONCE (fused._TracedCore); every
        program — 1-step jit, each K-step scan — replays the jaxpr."""
        self._core_closed = _TracedCore(core, example)

    def _build1(self):
        self._jit = _one_step_jit(self._core_closed, label=self._audit_key)

    def _buildk(self, k):
        # mcarry_index=3: the metric accumulator's slot in the gluon
        # inner carry (ws, auxs, ss, mcarry, t_vec) — stacked per step
        # so the handler burst can observe per-batch metric state
        jitk = self._scan_jit if getattr(self, "_scan_jit", None) is not None \
            else _scan_block_jit(self._core_closed, mcarry_index=3,
                                 label=self._audit_key)
        self._scan_jit = jitk
        self._jit_block[k] = jitk
        return jitk

    # -- per step ------------------------------------------------------------
    def _ensure_states(self):
        upd = self._updater
        need = [(i, p) for i, p in zip(self._indices, self._train_params)
                if i not in upd.states]
        if not need:
            return
        # ONE compiled program creates every state (fused.py helper); the
        # per-parameter eager path costs a round trip per op on a remote
        # device and dominated Estimator's time-to-first-batch
        from ..fused import create_states_on_device
        states = create_states_on_device(
            self._opt, [i for i, _ in need],
            [p.data()._data for _, p in need], self._ctx)
        if states is not None:
            for (i, _), s in zip(need, states):
                upd.states[i] = s
                upd.states_synced[i] = True
            return
        for i, p in need:
            upd.states[i] = \
                self._opt.create_state_multi_precision(i, p.data())
            upd.states_synced[i] = True

    def __call__(self, data, label, batch_size):
        """Run one fused Gluon step; returns True when handled (params,
        optimizer state, aux and metrics all updated)."""
        return self._dispatch([(data, label)], batch_size)

    def call_block(self, pairs, batch_size):
        """Run len(pairs) steps as ONE `lax.scan` dispatch."""
        return self._dispatch(list(pairs), batch_size)

    def _dispatch(self, pairs, batch_size):
        if self.broken:
            return False
        import jax
        k = len(pairs)

        trainer = self._trainer
        if not trainer._kv_initialized:
            trainer._init_kvstore()
        if trainer._kvstore is not None:
            return False   # multi-device/dist reductions: eager loop
        if self._opt is not trainer._optimizer or \
                self._updater is not trainer._updaters[0]:
            # load_states() replaces the updater's optimizer (and the
            # states dict): rebuild around the restored objects
            self._opt = trainer._optimizer
            self._updater = trainer._updaters[0]
            self._jit = None
            self._jit_block = {}
            self._core_closed = None
            self._core_sig = None
            self._core_cache = {}   # cached programs trace the OLD optimizer
            self._carry = None
            self._t_vec = None
        opt = self._opt
        opt.rescale_grad = trainer._scale / batch_size
        try:
            self._ensure_states()
        except Exception:
            # deferred-init parameters: the eager loop's first forward
            # materializes them; retry fusing from the next batch
            return False

        # eligibility BEFORE any transfer: a rejected block must not cost
        # K device_puts (the eager fallback would re-upload the batches)
        sig0 = None
        for data, label in pairs:
            if not isinstance(data, NDArray) or not isinstance(label, NDArray):
                return False
            s = (tuple(data.shape), str(data.dtype),
                 tuple(label.shape), str(label.dtype))
            if sig0 is None:
                sig0 = s
            elif s != sig0:
                return False   # ragged block cannot share one program
        in_sig = sig0
        from .. import analysis as _analysis
        _analysis.recompile.note(
            self._audit_key, ("data", "label"),
            ((sig0[0], sig0[1]), (sig0[2], sig0[3])))
        dev = self._ctx.jax_device
        staged = [(jax.device_put(d._data, dev), jax.device_put(l._data, dev))
                  for d, l in pairs]

        carry = self._carry if self._carry is not None and \
            getattr(self, "_carry_sdict", None) is self._updater.states and \
            getattr(self, "_carry_sig", None) == in_sig and \
            all(p._data[0]._data is w
                for p, w in zip(self._train_params, self._carry[0])) and \
            all(p._data[0]._data is a
                for p, a in zip(self._aux_params, self._carry[1])) \
            else None

        states = [self._updater.states[i] for i in self._indices]
        if carry is not None:
            ws, auxs, ss = carry
        else:
            ws = [p._data[0]._data for p in self._train_params]
            auxs = tuple(p._data[0]._data for p in self._aux_params)
            ss = tuple(_state_data(s) for s in states)
            # cold dispatch: params/states may be externally staged
            # (initialize, load_parameters, trainer-state restore) —
            # donated host-staged buffers corrupt under the AOT path;
            # re-own through one XLA copy (fused.reown_for_donation)
            from ..fused import reown_for_donation
            ws, auxs, ss = reown_for_donation((ws, auxs, ss))

        mcarry = []
        for m in self._metrics:
            pend = getattr(m, "_device_totals", None)
            if pend is None:
                import jax.numpy as jnp
                pend = (jax.device_put(jnp.zeros((), jnp.float32), dev),
                        jax.device_put(jnp.zeros((), jnp.int32), dev))
            mcarry.append(tuple(pend))

        counts_before = dict(opt._index_update_count)
        num_update_before = opt.num_update
        from ..fused import advance_hyper_rows
        rows, rescale_dev = advance_hyper_rows(opt, self._indices, k, self,
                                               dev)
        t_vec = self._t_vec if carry is not None else None
        if t_vec is None:
            from ..fused import reown_for_donation
            t_vec = reown_for_donation(jax.device_put(_np.asarray(
                [opt._index_update_count[i] - k for i in self._indices],
                _np.float32), dev))

        inner = (tuple(ws), tuple(auxs), ss, tuple(mcarry), t_vec)
        xs = [(dval, lval, lr_j, wd_j)
              for (dval, lval), (lr_j, wd_j) in zip(staged, rows)]

        if _analysis.enabled():
            self._step_no += k
            _analysis.donation.record(
                f"{self._audit_key} step {self._step_no}",
                self._donation_groups(ws, ss, auxs))

        if self._core_closed is not None and in_sig != self._core_sig:
            # signature changed: the traced core jaxpr is shape-
            # specialized — swap in the cached program set for this
            # signature or re-trace (churn recorded by the auditor above);
            # a ragged tail batch must not permanently break the fast path
            cached = self._core_cache.get(in_sig)
            if cached is not None:
                (self._core_closed, self._jit, self._scan_jit,
                 self._jit_block) = cached
            else:
                self._core_closed = None

        try:
            with _no_rng():
                if self._core_closed is None:
                    core = self._build_core()
                    self._trace_core(core, (inner, xs[0], rescale_dev))
                    self._jit = None
                    self._jit_block = {}
                    self._scan_jit = None
                if k == 1:
                    if self._jit is None:
                        self._build1()
                    new_inner, (mean_loss, out) = self._jit(
                        inner, xs[0], rescale_dev)
                    mys = None
                else:
                    jitk = self._jit_block.get(k) or self._buildk(k)
                    # ys (all K steps' losses/outputs) are available from
                    # the scan; handlers only read the latest, so expose
                    # the in-program last slice — mys (per-step metric
                    # carries) feeds the per-batch handler burst
                    new_inner, _ys, mys, (mean_loss, out) = jitk(
                        inner, tuple(xs), rescale_dev)
        except Exception as e:
            opt._index_update_count = counts_before
            opt.num_update = num_update_before
            self._carry = None
            self._t_vec = None
            self._block_view = None
            self.broken = True
            _raise_if_unrecoverable("gluon fused step", e,
                                    self._donation_groups(ws, ss, auxs))
            _log.warning("gluon fused step unavailable (%s); Estimator "
                         "uses the eager loop", str(e)[:300])
            return False

        new_ws, new_aux, new_ss, new_mcarry, new_t = new_inner
        # write back (params/aux/optimizer state are shared with the eager
        # path so the two stay interchangeable)
        for p, nw in zip(self._train_params, new_ws):
            p._data[0]._set_data(nw)
        for p, na in zip(self._aux_params, new_aux):
            p._data[0]._set_data(na)
        for s, ns in zip(states, new_ss):
            _state_write_back(s, ns)
        finals = []
        for m, pend in zip(self._metrics, new_mcarry):
            t = tuple(pend)
            m._device_totals = t
            finals.append(t)
        if mys is not None:
            # per-step metric exposure for the Estimator handler burst
            self._block_view = _BlockMetricView(self._metrics, mys, finals)
            self._block_view.arm()
        else:
            self._block_view = None
        self._t_vec = new_t
        self.last_loss = NDArray(mean_loss, ctx=self._ctx)
        self.last_outputs = NDArray(out, ctx=self._ctx)
        self._carry = ([p._data[0]._data for p in self._train_params],
                       tuple(p._data[0]._data for p in self._aux_params),
                       tuple(_state_data(s) for s in states))
        self._carry_sig = in_sig
        self._carry_sdict = self._updater.states
        self._core_sig = in_sig
        if len(self._core_cache) < 8 or in_sig in self._core_cache:
            self._core_cache[in_sig] = (self._core_closed, self._jit,
                                        self._scan_jit, self._jit_block)
        return True

    def set_block_cursor(self, j):
        """Expose logical step j's metric state to the Estimator's
        batch-j handler burst (per-step semantics for K>1 blocks)."""
        if self._block_view is not None:
            self._block_view.expose(j)

    def cached_programs(self):
        """Live CachedPrograms across every cached signature set."""
        progs = {}
        for p in (self._jit, getattr(self, "_scan_jit", None)):
            if p is not None and hasattr(p, "export_to"):
                progs[id(p)] = p
        for entry in self._core_cache.values():
            for p in entry[1:3]:
                if p is not None and hasattr(p, "export_to"):
                    progs[id(p)] = p
        return list(progs.values())

    def export_programs(self, directory):
        """Serialize compiled executables into `directory` (checkpoint
        ``programs/`` payload); returns entries written."""
        return sum(p.export_to(directory) for p in self.cached_programs())

    def compile_phase_stats(self):
        """Cold-start phase breakdown — the same artifact shape as
        `fused.FusedTrainStep.compile_phase_stats`, which only touches
        the attributes both step classes share (traced core, scan runs,
        unified-cache program wrappers)."""
        from ..fused import FusedTrainStep
        return FusedTrainStep.compile_phase_stats(self)
