"""Fused recurrent layers RNN/LSTM/GRU
(reference `python/mxnet/gluon/rnn/rnn_layer.py` — RNN:234, LSTM:328, GRU:433).

Parameters are stored per-layer/direction (`l0_i2h_weight`, `l0_h2h_weight`,
biases, `r0_*` for reverse) exactly like the reference so checkpoints map
1:1; at call time they are packed into the flat cuDNN-layout vector the fused
RNN op consumes (`ops/nn.py` RNN — lax.scan over time)."""
from __future__ import annotations

from ..block import HybridBlock
from ...base import MXNetError
from ... import ndarray as nd

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout, bidirectional,
                 input_size, i2h_weight_initializer, h2h_weight_initializer,
                 i2h_bias_initializer, h2h_bias_initializer, mode, **kwargs):
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC"), \
            f"Invalid layout {layout}; must be one of ['TNC' or 'NTC']"
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._i2h_weight_initializer = i2h_weight_initializer
        self._h2h_weight_initializer = h2h_weight_initializer
        self._i2h_bias_initializer = i2h_bias_initializer
        self._h2h_bias_initializer = h2h_bias_initializer

        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]
        ng, ni, nh = self._gates, input_size, hidden_size
        for i in range(num_layers):
            for j in ["l", "r"][:self._dir]:
                self._register_param(f"{j}{i}_i2h_weight", (ng * nh, ni),
                                     i2h_weight_initializer)
                self._register_param(f"{j}{i}_h2h_weight", (ng * nh, nh),
                                     h2h_weight_initializer)
                self._register_param(f"{j}{i}_i2h_bias", (ng * nh,),
                                     i2h_bias_initializer)
                self._register_param(f"{j}{i}_h2h_bias", (ng * nh,),
                                     h2h_bias_initializer)
            ni = nh * self._dir

    def _register_param(self, name, shape, init):
        p = self.params.get(name, shape=shape, init=init,
                            allow_deferred_init=True)
        self._reg_params[name] = p
        setattr(self, name, p)

    def __repr__(self):
        return f"{self.__class__.__name__}({self._input_size} -> " \
               f"{self._hidden_size}, {self._layout}" + \
               (", bidirectional" if self._dir == 2 else "") + ")"

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial states (reference `rnn_layer.py begin_state`)."""
        from ... import ndarray as nd_mod
        states = []
        for info in self.state_info(batch_size):
            states.append(nd_mod.zeros(**{**info, **kwargs}))
        return states

    def hybrid_forward(self, F, inputs, states=None, **params):
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, dim1=0, dim2=1)
        if states is None:
            batch_size = inputs.shape[1] if hasattr(inputs, "shape") else 0
            states = self.begin_state(batch_size, ctx=inputs.context
                                      if hasattr(inputs, "context") else None)
        if not isinstance(states, (list, tuple)):
            states = [states]
        flat = self._pack_params(F, params)
        rnn_args = [inputs, flat] + list(states)
        out = F.RNN(*rnn_args, state_size=self._hidden_size,
                    num_layers=self._num_layers,
                    bidirectional=self._dir == 2, mode=self._mode,
                    p=self._dropout, state_outputs=True)
        outputs, out_states = out[0], list(out[1:])
        if self._layout == "NTC":
            outputs = F.swapaxes(outputs, dim1=0, dim2=1)
        return outputs, out_states

    def _pack_params(self, F, params):
        """Pack per-layer params into the flat cuDNN layout: all weights
        (layer-major, Wx then Wh per direction), then all biases."""
        chunks = []
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                chunks.append(F.Reshape(params[f"{j}{i}_i2h_weight"],
                                        shape=(-1,)))
                chunks.append(F.Reshape(params[f"{j}{i}_h2h_weight"],
                                        shape=(-1,)))
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                chunks.append(params[f"{j}{i}_i2h_bias"])
                chunks.append(params[f"{j}{i}_h2h_bias"])
        return F.Concat(*chunks, dim=0, num_args=len(chunks))

    def forward(self, inputs, states=None):
        """Eager path handles optional states before dispatching."""
        from ...ndarray.ndarray import NDArray
        if isinstance(inputs, NDArray):
            batch_axis = 0 if self._layout == "NTC" else 1
            batch_size = inputs.shape[batch_axis]
            skip_states = states is None
            if skip_states:
                states = self.begin_state(batch_size, ctx=inputs.context)
            if isinstance(states, NDArray):
                states = [states]
            ctx = inputs.context
            try:
                params = {name: p.data(ctx)
                          for name, p in self._reg_params.items()}
            except Exception:
                self._deferred_infer_shape_rnn(inputs)
                for p in self.collect_params().values():
                    if p._deferred_init:
                        p._finish_deferred_init()
                params = {name: p.data(ctx)
                          for name, p in self._reg_params.items()}
            out, out_states = self.hybrid_forward(nd, inputs, states, **params)
            return out if skip_states else (out, out_states)
        raise MXNetError("RNN layers require NDArray inputs in eager mode")

    def _deferred_infer_shape_rnn(self, inputs):
        ni = inputs.shape[2] if self._layout == "TNC" else inputs.shape[2]
        ng, nh = self._gates, self._hidden_size
        cur = ni
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                self._reg_params[f"{j}{i}_i2h_weight"].shape = (ng * nh, cur)
            cur = nh * self._dir


class RNN(_RNNLayer):
    """Vanilla RNN (reference `rnn_layer.py:234 RNN`)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """LSTM (reference `rnn_layer.py:328 LSTM`)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "lstm", **kwargs)

    def state_info(self, batch_size=0):
        shape = (self._num_layers * self._dir, batch_size, self._hidden_size)
        return [{"shape": shape, "__layout__": "LNC"},
                {"shape": shape, "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """GRU (reference `rnn_layer.py:433 GRU`)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
