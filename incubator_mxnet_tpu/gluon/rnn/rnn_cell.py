"""Recurrent cells (reference `python/mxnet/gluon/rnn/rnn_cell.py` :105-730).

Cells are fine-grained recurrent units with explicit `unroll`; under
hybridize the unrolled graph compiles to one XLA computation (the reference
runs it as a CachedOp; control-flow `foreach` maps to `lax.scan` via the
contrib symbolic path)."""
from __future__ import annotations

from ..block import Block, HybridBlock
from ...base import MXNetError

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "DropoutCell", "ModifierCell",
           "ZoneoutCell", "ResidualCell", "BidirectionalCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _format_sequence(length, inputs, layout, merge, F=None):
    from ...ndarray.ndarray import NDArray
    from ... import ndarray as nd_mod
    from ... import symbol as sym_mod
    axis = layout.find("T")
    batch_axis = layout.find("N")
    if isinstance(inputs, (list, tuple)):
        F = nd_mod if isinstance(inputs[0], NDArray) else sym_mod
        in_axis = 0
        if merge is True:
            inputs = F.stack(*inputs, axis=axis, num_args=len(inputs))
        return inputs, axis, F, len(inputs) if isinstance(inputs, (list, tuple)) else length
    F = nd_mod if isinstance(inputs, NDArray) else sym_mod
    if merge is False:
        seq = F.split(inputs, num_outputs=length, axis=axis, squeeze_axis=True)
        if not isinstance(seq, (list, tuple)):
            seq = [seq]
        return list(seq), axis, F, length
    return inputs, axis, F, length


class RecurrentCell(Block):
    """Base recurrent cell (reference `rnn_cell.py:RecurrentCell`)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError()

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called " \
            "directly. Call the modifier cell instead."
        from ... import ndarray as nd_mod
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            if info is not None:
                info = dict(info)
                info.pop("__layout__", None)
                states.append((func or nd_mod.zeros)(**{**info, **kwargs}))
            else:
                states.append((func or nd_mod.zeros)(**kwargs))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unroll over time (reference `rnn_cell.py unroll`).

        Symbolic sequences with merged outputs emit ONE `_foreach` node
        (`lax.scan` in the compiled program) instead of T copies of the
        cell body — the TPU-first form of the reference's
        `control_flow.cc` foreach path; cells that cannot scan (aux-state
        layers in the body) fall back to the classic static unroll."""
        self.reset()
        from ...symbol.symbol import Symbol as _SymT
        if merge_outputs and valid_length is None and \
                isinstance(inputs, _SymT) and begin_state is not None:
            try:
                return self._unroll_foreach(length, inputs, begin_state,
                                            layout)
            except Exception:
                self.reset()   # e.g. BatchNorm in the body: static unroll
        inputs, axis, F, length = _format_sequence(length, inputs, layout,
                                                   False)
        if begin_state is None:
            batch_size = inputs[0].shape[0]
            begin_state = self.begin_state(batch_size,
                                           ctx=inputs[0].context
                                           if hasattr(inputs[0], "context")
                                           else None)
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        if merge_outputs:
            outputs = F.stack(*outputs, axis=layout.find("T"),
                              num_args=len(outputs))
        return outputs, states

    def _unroll_foreach(self, length, inputs, begin_state, layout):
        """One-scan unroll: cell body traced once into a `_foreach`
        (shared lowering: symbol/contrib.py foreach_unroll)."""
        from ...symbol.contrib import foreach_unroll
        return foreach_unroll(lambda x, st: self(x, st), inputs,
                              begin_state, layout, length)

    def forward(self, inputs, states):
        self._counter += 1
        return super().forward(inputs, states)


class HybridRecurrentCell(RecurrentCell, HybridBlock):
    """Hybridizable recurrent cell."""

    def __init__(self, prefix=None, params=None):
        RecurrentCell.__init__(self, prefix=prefix, params=params)

    def forward(self, inputs, states):
        self._counter += 1
        from ...ndarray.ndarray import NDArray
        from ... import ndarray as nd_mod
        if isinstance(inputs, NDArray):
            ctx = inputs.context
            try:
                params = {name: p.data(ctx)
                          for name, p in self._reg_params.items()}
            except Exception:
                for p in self._reg_params.values():
                    if p.shape and 0 in p.shape:
                        self._infer_cell_shape(inputs)
                        break
                for p in self.collect_params().values():
                    if p._deferred_init:
                        p._finish_deferred_init()
                params = {name: p.data(ctx)
                          for name, p in self._reg_params.items()}
            return self.hybrid_forward(nd_mod, inputs, states, **params)
        from ... import symbol as sym_mod
        params = {name: p.var() for name, p in self._reg_params.items()}
        return self.hybrid_forward(sym_mod, inputs, states, **params)

    def _infer_cell_shape(self, inputs):
        in_dim = inputs.shape[-1]
        for name, p in self._reg_params.items():
            if "i2h_weight" in name and p.shape and p.shape[-1] == 0:
                p.shape = (p.shape[0], in_dim)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class RNNCell(HybridRecurrentCell):
    """Elman cell (reference `rnn_cell.py RNNCell`)."""

    def __init__(self, hidden_size, activation="tanh",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = self.params.get("i2h_weight",
                                          shape=(hidden_size, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get("h2h_weight",
                                          shape=(hidden_size, hidden_size),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        self.i2h_bias = self.params.get("i2h_bias", shape=(hidden_size,),
                                        init=i2h_bias_initializer,
                                        allow_deferred_init=True)
        self.h2h_bias = self.params.get("h2h_bias", shape=(hidden_size,),
                                        init=h2h_bias_initializer,
                                        allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size, name="i2h")
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size, name="h2h")
        output = F.Activation(i2h + h2h, act_type=self._activation,
                              name="out")
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    """LSTM cell (reference `rnn_cell.py LSTMCell`); gate order i,f,g,o
    matching the fused op's cuDNN layout."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get("i2h_weight",
                                          shape=(4 * hidden_size, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get("h2h_weight",
                                          shape=(4 * hidden_size, hidden_size),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        self.i2h_bias = self.params.get("i2h_bias", shape=(4 * hidden_size,),
                                        init=i2h_bias_initializer,
                                        allow_deferred_init=True)
        self.h2h_bias = self.params.get("h2h_bias", shape=(4 * hidden_size,),
                                        init=h2h_bias_initializer,
                                        allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size * 4, name="i2h")
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size * 4, name="h2h")
        gates = i2h + h2h
        slices = F.SliceChannel(gates, num_outputs=4, axis=1, name="slice")
        in_gate = F.Activation(slices[0], act_type="sigmoid", name="i")
        forget_gate = F.Activation(slices[1], act_type="sigmoid", name="f")
        in_transform = F.Activation(slices[2], act_type="tanh", name="c")
        out_gate = F.Activation(slices[3], act_type="sigmoid", name="o")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * F.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    """GRU cell (reference `rnn_cell.py GRUCell`); gate order r,z,n."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get("i2h_weight",
                                          shape=(3 * hidden_size, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get("h2h_weight",
                                          shape=(3 * hidden_size, hidden_size),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        self.i2h_bias = self.params.get("i2h_bias", shape=(3 * hidden_size,),
                                        init=i2h_bias_initializer,
                                        allow_deferred_init=True)
        self.h2h_bias = self.params.get("h2h_bias", shape=(3 * hidden_size,),
                                        init=h2h_bias_initializer,
                                        allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_state_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size * 3, name="i2h")
        h2h = F.FullyConnected(prev_state_h, h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size * 3, name="h2h")
        i2h_s = F.SliceChannel(i2h, num_outputs=3, axis=1, name="i2h_slice")
        h2h_s = F.SliceChannel(h2h, num_outputs=3, axis=1, name="h2h_slice")
        reset_gate = F.Activation(i2h_s[0] + h2h_s[0], act_type="sigmoid")
        update_gate = F.Activation(i2h_s[1] + h2h_s[1], act_type="sigmoid")
        next_h_tmp = F.Activation(i2h_s[2] + reset_gate * h2h_s[2],
                                  act_type="tanh")
        next_h = (1. - update_gate) * next_h_tmp + update_gate * prev_state_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Stack of cells (reference `rnn_cell.py SequentialRNNCell`)."""

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            inputs, new_states = cell(inputs, states[p:p + n])
            p += n
            next_states.extend(new_states)
        return inputs, next_states

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        inputs, _, F, length = _format_sequence(length, inputs, layout, False)
        if begin_state is None:
            batch_size = inputs[0].shape[0]
            begin_state = self.begin_state(batch_size=batch_size,
                                           ctx=inputs[0].context)
        p = 0
        next_states = []
        cells = list(self._children.values())
        for i, cell in enumerate(cells):
            n = len(cell.state_info())
            states = begin_state[p:p + n]
            p += n
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=states, layout=layout,
                merge_outputs=None if i < len(cells) - 1 else merge_outputs)
            next_states.extend(states)
        return inputs, next_states


class DropoutCell(HybridRecurrentCell):
    """Reference `rnn_cell.py DropoutCell`."""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix, params)
        assert isinstance(rate, (int, float))
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class ModifierCell(HybridRecurrentCell):
    """Base for cells that modify another cell (reference ModifierCell)."""

    def __init__(self, base_cell):
        assert not base_cell._modified, \
            "Cell %s is already modified." % base_cell.name
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + self._alias(),
                         params=None)
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(batch_size, func=func, **kwargs)
        self.base_cell._modified = True
        return begin


class ZoneoutCell(ModifierCell):
    """Reference `rnn_cell.py ZoneoutCell`."""

    def __init__(self, base_cell, zoneout_outputs=0., zoneout_states=0.):
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def hybrid_forward(self, F, inputs, states):
        cell, p_outputs, p_states = self.base_cell, self.zoneout_outputs, \
            self.zoneout_states
        next_output, next_states = cell(inputs, states)
        mask = lambda p, like: F.Dropout(F.ones_like(like), p=p)
        prev_output = self._prev_output
        if prev_output is None:
            prev_output = F.zeros_like(next_output)
        output = (F.where(mask(p_outputs, next_output), next_output,
                          prev_output) if p_outputs != 0. else next_output)
        new_states = ([F.where(mask(p_states, new_s), new_s, old_s)
                       for new_s, old_s in zip(next_states, states)]
                      if p_states != 0. else next_states)
        self._prev_output = output
        return output, new_states


class ResidualCell(ModifierCell):
    """Reference `rnn_cell.py ResidualCell`."""

    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states

    def _alias(self):
        return "residual"


class BidirectionalCell(HybridRecurrentCell):
    """Reference `rnn_cell.py BidirectionalCell`."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise NotImplementedError("Bidirectional cannot be stepped. "
                                  "Please use unroll")

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        inputs, axis, F, length = _format_sequence(length, inputs, layout,
                                                   False)
        if begin_state is None:
            batch_size = inputs[0].shape[0]
            begin_state = self.begin_state(batch_size=batch_size,
                                           ctx=inputs[0].context)
        states = begin_state
        l_cell, r_cell = self._children.values()
        n_l = len(l_cell.state_info())
        l_outputs, l_states = l_cell.unroll(length, inputs=inputs,
                                            begin_state=states[:n_l],
                                            layout=layout,
                                            merge_outputs=False)
        r_outputs, r_states = r_cell.unroll(length,
                                            inputs=list(reversed(inputs)),
                                            begin_state=states[n_l:],
                                            layout=layout,
                                            merge_outputs=False)
        outputs = [F.Concat(l_o, r_o, dim=1, num_args=2)
                   for l_o, r_o in zip(l_outputs, reversed(r_outputs))]
        if merge_outputs:
            outputs = F.stack(*outputs, axis=layout.find("T"),
                              num_args=len(outputs))
        return outputs, l_states + r_states
