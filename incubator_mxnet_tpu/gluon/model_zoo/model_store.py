"""Pretrained model store (reference `model_zoo/model_store.py:30-41`).

Zero-egress environment: pretrained weights cannot be downloaded.  If weight
files are placed under ``root`` manually, they are used; otherwise a clear
error explains the situation.
"""
from __future__ import annotations

import os

from ...base import MXNetError

_model_sha1 = {}  # name -> sha1 (reference populates from its registry)


def get_model_file(name, root="~/.mxnet/models"):
    root = os.path.expanduser(root)
    file_path = os.path.join(root, f"{name}.params")
    if os.path.exists(file_path):
        return file_path
    raise MXNetError(
        f"Pretrained weights for '{name}' not found at {file_path} and this "
        "environment has no network access. Place the .params file there "
        "manually, or construct the model with pretrained=False.")


def purge(root="~/.mxnet/models"):
    root = os.path.expanduser(root)
    if os.path.isdir(root):
        for f in os.listdir(root):
            if f.endswith(".params"):
                os.remove(os.path.join(root, f))
