"""Gluon utilities (reference `python/mxnet/gluon/utils.py`)."""
from __future__ import annotations

import math

import numpy as np

from ..base import MXNetError
from ..context import Context, cpu
from ..ndarray.ndarray import NDArray, array

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split along batch axis (reference `utils.py split_data`)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            f"data with shape {data.shape} cannot be evenly split into "
            f"{num_slice} slices along axis {batch_axis}. Use a batch size "
            f"that's a multiple of {num_slice} or set even_split=False.")
    n_each = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * n_each
        end = (i + 1) * n_each if i < num_slice - 1 else size
        sl = [slice(None)] * data.ndim
        sl[batch_axis] = slice(begin, end)
        slices.append(data[tuple(sl)])
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split + place each shard on its context (reference `utils.py`)."""
    if not isinstance(data, NDArray):
        data = array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale so total L2 norm <= max_norm (reference `utils.py`)."""
    assert len(arrays) > 0
    ctx = arrays[0].context
    total_norm_sq = 0.0
    for arr in arrays:
        a = arr.asnumpy().astype(np.float64)
        total_norm_sq += float((a * a).sum())
    total_norm = math.sqrt(total_norm_sq)
    if check_isfinite and not math.isfinite(total_norm):
        import warnings
        warnings.warn(UserWarning(
            "nan or inf is detected. Clipping results will be undefined."),
            stacklevel=2)
    scale = max_norm / (total_norm + 1e-8)
    if scale < 1.0:
        for arr in arrays:
            arr._set_data(arr._data * scale)
    return total_norm


def check_sha1(filename, sha1_hash):
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None,
             retries=5, verify_ssl=True):
    """Reference `utils.py download` — this environment has zero egress."""
    raise MXNetError(
        "download() is unavailable: this environment has no network access. "
        "Place files manually and point APIs at the local path.")
