"""Gluon Trainer (reference `python/mxnet/gluon/trainer.py:27` —
_init_kvstore:158, step:254, _allreduce_grads:304)."""
from __future__ import annotations

from ..base import MXNetError
from .. import optimizer as opt
from .. import kvstore as kvs
from .parameter import ParameterDict, Parameter

__all__ = ["Trainer"]


class Trainer:
    """Parameter updater (reference `gluon/trainer.py:27`).

    TPU extensions: updates apply as ONE donated XLA program
    (`fused.FusedOptimizer`); ``zero=mesh`` (or ``(mesh, axis)``) shards
    every optimizer-state tensor over the mesh's first (or named) axis —
    ZeRO state partitioning, the mesh reading of the reference's
    range-sharded parameter servers.  Combine with
    `parallel.shard_block` for tensor-parallel parameters.
    """

    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None, zero=None,
                 mesh=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                f"got {type(params)}.")
        self._params = []
        self._param2idx = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    f"got list of {type(param)}.")
            self._param2idx[param.name] = i
            self._params.append(param)
            param._set_trainer(self) if hasattr(param, "_set_trainer") else None
        self._compression_params = compression_params
        optimizer_params = optimizer_params if optimizer_params else {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._contexts = self._check_contexts()
        self._init_optimizer(optimizer, optimizer_params)
        self._kv_initialized = False
        self._kvstore_type = kvstore
        self._update_on_kvstore_arg = update_on_kvstore
        self._kvstore = None
        self._update_on_kvstore = None
        self._fused = None
        if mesh is None:
            # MXNET_MESH spec (or None).  On the Trainer, `mesh=` exists
            # to resolve `zero=True` (which dp axis shards the optimizer
            # state) — the per-parameter update path itself is mesh-free;
            # the composed-mesh TRAINING lever lives in Module.fit /
            # parallel's explicit SPMD steps.
            from ..parallel.mesh import mesh_from_spec
            try:
                mesh = mesh_from_spec()
            except Exception:
                mesh = None
        self._mesh = mesh
        if zero is True:
            if mesh is None:
                raise MXNetError(
                    "Trainer(zero=True) needs a mesh: pass mesh= (or set "
                    "MXNET_MESH), or hand zero= the mesh directly")
            zero = mesh
        elif zero is False:
            zero = None
        if zero is not None and not isinstance(zero, tuple):
            # optimizer state shards over the DATA-parallel axis (every
            # dp rank holds the full params and a 1/N state shard) — on
            # a composed mesh the dp axis is found by name, not position
            from ..parallel.mesh import dp_axis_of
            zero = (zero, dp_axis_of(zero))
        self._zero = zero  # (mesh, axis) for sharded optimizer state

    def _check_contexts(self):
        contexts = None
        for param in self._params:
            ctx = param.list_ctx()
            assert contexts is None or contexts == ctx, \
                "All Parameters must be initialized on the same set of contexts"
            contexts = ctx
        return contexts

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an Optimizer " \
                "instance"
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)
                          for _ in self._contexts]

    def _init_kvstore(self):
        if len(self._contexts) > 1 or "dist" in str(self._kvstore_type):
            kv = kvs.create(self._kvstore_type) if isinstance(
                self._kvstore_type, str) else self._kvstore_type
            if self._compression_params:
                kv.set_gradient_compression(self._compression_params)
            for i, param in enumerate(self._params):
                if param.grad_req != "null":
                    kv.init(i, param.list_data()[0])
            self._kvstore = kv
            self._update_on_kvstore = False
        else:
            self._kvstore = None
            self._update_on_kvstore = False
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.lr if self._optimizer.lr_scheduler is None \
            else self._optimizer.lr_scheduler(self._optimizer.num_update)

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce + update (reference `trainer.py:254 step`)."""
        from .. import analysis as _analysis
        with _analysis.hostsync.hot_loop("Trainer.step"):
            if not self._kv_initialized:
                self._init_kvstore()
            self._optimizer.rescale_grad = self._scale / batch_size
            self._allreduce_grads()
            self._update(ignore_stale_grad)

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        live = []
        for i, p in enumerate(self._params):
            if p.grad_req != "null":
                grads = p.list_grad()
                if len(grads) > 1:
                    live.append((i, grads))
        if not live:
            return
        if getattr(self._kvstore, "prefers_batched_push", False):
            # ONE batched push/pull pair: the collective store packs the
            # whole key list into size-capped buckets and dispatches
            # O(buckets) overlapped all-reduces, not one per parameter
            keys = [i for i, _ in live]
            grads = [g for _, g in live]
            self._kvstore.push(keys, grads)
            self._kvstore.pull(keys, grads)
            return
        for i, grads in live:
            self._kvstore.push(i, grads, priority=-i)
            self._kvstore.pull(i, grads, priority=-i)

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        """Apply all parameter updates.

        TPU fast path: ONE donated XLA program applies the optimizer for
        every parameter (`fused.FusedOptimizer`), replacing the reference's
        per-parameter fused-op dispatches (`trainer.py:254 step` →
        `optimizer_op.cc` kernels) — on TPU each dispatch is a host round
        trip, so the multi-tensor apply is the only way `Trainer.step`
        keeps up with a jitted forward/backward."""
        live = [(i, p) for i, p in enumerate(self._params)
                if p.grad_req != "null"]
        if not live:
            return
        if self._fused is None:
            from .. import fused as _fused
            self._fused = [_fused.FusedOptimizer(u.optimizer)
                           for u in self._updaters]
        for k, upd in enumerate(self._updaters):
            indices, ws, gs, ss = [], [], [], []
            for i, param in live:
                arr = param.list_data()[k]
                grad = param.list_grad()[k]
                if i not in upd.states:
                    upd.states[i] = \
                        upd.optimizer.create_state_multi_precision(i, arr)
                    upd.states_synced[i] = True
                    self._place_state(upd.states[i], arr)
                indices.append(i)
                ws.append(arr)
                gs.append(grad)
                ss.append(upd.states[i])
            self._fused[k](indices, ws, gs, ss)

    def _place_state(self, state, weight):
        """Lay freshly-created optimizer state out to match the weight's
        residency: ZeRO-sharded when ``zero=`` was given, replicated on the
        weight's mesh when the weight is mesh-sharded (mixing mesh weights
        with single-device state would fail the fused update jit)."""
        from ..parallel.gluon_bridge import shard_state_for_zero
        if self._zero is not None:
            shard_state_for_zero(state, *self._zero)
            return
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        ws = getattr(weight._data, "sharding", None)
        if not isinstance(ws, NamedSharding):
            return
        rep = NamedSharding(ws.mesh, P())
        from ..ndarray.ndarray import NDArray
        for leaf in jax.tree_util.tree_leaves(
                state, is_leaf=lambda x: isinstance(x, NDArray)):
            if isinstance(leaf, NDArray):
                leaf._set_data(jax.device_put(leaf._data, rep))

    def get_checkpoint_state(self):
        """Optimizer slots + the pickled optimizer (update counts,
        LR-scheduler position) as one bytes blob — what an elastic
        checkpoint stores per Trainer (checkpoint/state.py)."""
        assert self._optimizer is not None
        return self._updaters[0].get_states(dump_optimizer=True)

    def set_checkpoint_state(self, blob):
        """Restore a `get_checkpoint_state` blob; every context's updater
        adopts the restored slots and the ONE restored optimizer so
        update counting continues where the checkpoint left off."""
        if not self._kv_initialized:
            self._init_kvstore()
        for updater in self._updaters:
            updater.set_states(blob)
            updater.optimizer = self._updaters[0].optimizer
        self._optimizer = self._updaters[0].optimizer
        self._optimizer.param_dict = {i: p for i, p in
                                      enumerate(self._params)}
        # fused multi-tensor apply caches per-optimizer programs: rebuild
        # against the restored optimizer instance
        self._fused = None

    def save_states(self, fname):
        with open(fname, "wb") as f:
            f.write(self.get_checkpoint_state())

    def load_states(self, fname):
        with open(fname, "rb") as f:
            self.set_checkpoint_state(f.read())
