"""Gluon Block / HybridBlock / SymbolBlock
(reference `python/mxnet/gluon/block.py` — Block:126, HybridBlock:672,
_build_cache:749 → CachedOp:786, SymbolBlock:953).

`hybridize()` = trace `hybrid_forward` once with Symbols, then compile the
graph to a single XLA computation via the shared graph evaluator — the exact
TPU analogue of the reference's CachedOp JIT (trace to nnvm graph, cached
optimized replay), with jax.jit's signature cache playing the role of
CachedOp's re-trace-on-new-shape check (`cached_op.cc:265`).
"""
from __future__ import annotations

import copy
import re
import threading

from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..ndarray.ndarray import NDArray, invoke
from .. import ndarray as nd
from ..ops.registry import OpDef
from .parameter import Parameter, ParameterDict, DeferredInitializationError

__all__ = ["Block", "HybridBlock", "SymbolBlock"]


class _BlockScope:
    """Name manager for Block prefixes (reference `block.py:_BlockScope`)."""

    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None
        self._name_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                from ..symbol.symbol import _NameManager
                prefix = _NameManager.next_name(hint + "_") + "_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            prefix = f"{hint}{count}_"
            current._counter[hint] = count + 1
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        if self._block._empty_prefix:
            return
        _BlockScope._current.value = self._old_scope


class Block:
    """Base building block (reference `block.py:126 Block`)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(prefix, params,
                                                        self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children = {}
        self._reg_params = {}
        self._forward_hooks = {}
        self._forward_pre_hooks = {}

    def _alias(self):
        return self.__class__.__name__.lower()

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join(f"  ({key}): {_indent(repr(block), 2)}"
                           for key, block in self._children.items())
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __setattr__(self, name, value):
        if hasattr(self, name):
            existing = getattr(self, name)
            if isinstance(existing, (Parameter, Block)) and \
                    not isinstance(value, type(existing)) and \
                    not isinstance(existing, type(value)):
                raise TypeError(f"Changing attribute type for {name} from "
                                f"{type(existing)} to {type(value)} is not "
                                "allowed.")
        if isinstance(value, Block):
            self.register_child(value, name)
        elif isinstance(value, Parameter):
            assert name not in self._reg_params or \
                self._reg_params[name] is value, \
                "Overriding Parameter attribute %s is not allowed." % name
            self._reg_params[name] = value
        super().__setattr__(name, value)

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        """Reference `block.py name_scope`."""
        return self._scope

    @property
    def params(self):
        return self._params

    def collect_params(self, select=None):
        """All Parameters of this block and children (reference
        `block.py collect_params`)."""
        ret = ParameterDict(self._params.prefix)
        if not select:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({name: value for name, value in self.params.items()
                        if pattern.match(name)})
        for cld in self._children.values():
            ret.update(cld.collect_params(select=select))
        return ret

    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block

    def register_forward_hook(self, hook):
        handle = len(self._forward_hooks)
        self._forward_hooks[handle] = hook
        return handle

    def register_forward_pre_hook(self, hook):
        handle = len(self._forward_pre_hooks)
        self._forward_pre_hooks[handle] = hook
        return handle

    def apply(self, fn):
        for cld in self._children.values():
            cld.apply(fn)
        fn(self)
        return self

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        from .. import initializer as init_mod
        self.collect_params().initialize(init or init_mod.Uniform(), ctx,
                                         verbose, force_reinit)

    def hybridize(self, active=True, **kwargs):
        for cld in self._children.values():
            cld.hybridize(active, **kwargs)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    def save_parameters(self, filename, deduplicate=False):
        """Reference `block.py:314 save_parameters` — keys are the
        prefix-stripped structural names."""
        params = self._collect_params_with_prefix()
        arg_dict = {key: val._reduce() for key, val in params.items()}
        nd.save(filename, arg_dict)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current"):
        """Reference `block.py:356 load_parameters`."""
        loaded = nd.load(filename)
        params = self._collect_params_with_prefix()
        if not loaded and not params:
            return
        if not any("." in k for k in loaded):
            # legacy full-name format
            full = self.collect_params()
            full.load(filename, ctx, allow_missing, ignore_extra,
                      self.prefix)
            return
        if not allow_missing:
            for name in params:
                assert name in loaded, \
                    f"Parameter '{name}' is missing in file '{filename}'"
        for name in loaded:
            if name not in params:
                if not ignore_extra:
                    raise ValueError(
                        f"Parameter '{name}' loaded from file '{filename}' "
                        "is not present in this Block")
                continue
            param = params[name]
            value = loaded[name]
            if param._data is None:
                param.shape = value.shape
                if param._deferred_init:
                    init, pctx, default_init, _ = param._deferred_init
                    param._deferred_init = (
                        init, [ctx] if isinstance(ctx, Context) else
                        (ctx or pctx), default_init, value)
                    param._finish_deferred_init()
                else:
                    param.initialize(ctx=ctx or [cpu()])
                    param.set_data(value)
            else:
                param.set_data(value)

    save_params = save_parameters
    load_params = load_parameters

    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + key: val for key, val in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def __call__(self, *args):
        for hook in self._forward_pre_hooks.values():
            hook(self, args)
        out = self.forward(*args)
        for hook in self._forward_hooks.values():
            hook(self, args, out)
        return out

    def forward(self, *args):
        raise NotImplementedError

    def summary(self, *inputs):
        """Print per-layer summary by running a forward with hooks."""
        rows = []

        def add_hook(block):
            def hook(blk, inp, out):
                o = out[0] if isinstance(out, (list, tuple)) else out
                n_params = sum(int(p.data().size) for p in
                               blk._reg_params.values()
                               if p._data is not None)
                rows.append((blk.name, type(blk).__name__,
                             tuple(o.shape) if hasattr(o, "shape") else "?",
                             n_params))
            return block.register_forward_hook(hook)

        handles = []
        def walk(b):
            handles.append((b, add_hook(b)))
            for c in b._children.values():
                walk(c)
        walk(self)
        self(*inputs)
        for b, h in handles:
            b._forward_hooks.pop(h, None)
        print(f"{'Layer':<30}{'Type':<20}{'Output Shape':<24}{'Params':<12}")
        print("-" * 86)
        total = 0
        for name, typ, shape, n in rows:
            print(f"{name:<30}{typ:<20}{str(shape):<24}{n:<12}")
            total += n
        print("-" * 86)
        print(f"Total params: {total}")


def _indent(s, num_spaces):
    lines = s.split("\n")
    if len(lines) == 1:
        return s
    first = lines.pop(0)
    return first + "\n" + "\n".join(" " * num_spaces + line for line in lines)


class _CachedGraph:
    """Compiled trace of a HybridBlock (the CachedOp, `cached_op.h:68`)."""

    _counter = [0]

    def __init__(self, symbol, n_data, data_names, block):
        from ..symbol.symbol import graph_eval_fn
        self.symbol = symbol
        self.block = block
        self._fns = {}
        # build once to learn input ordering + rng/aux structure
        fn, arg_nodes, aux_nodes, n_rng = graph_eval_fn(symbol, False)
        graph_eval_fn(symbol, True)
        self.arg_names = [n.name for n in arg_nodes]
        self.aux_names = [n.name for n in aux_nodes]
        self.n_rng = n_rng
        self.data_names = data_names
        n_out = len(symbol._entries)
        _CachedGraph._counter[0] += 1
        uid = _CachedGraph._counter[0]

        cache = {}

        def op_fn(params, *arrays):
            import jax
            is_train = bool(params.get("_train", False))
            if is_train not in cache:
                # scan-over-layers: identical repeated blocks in the
                # hybridized graph lower to one lax.scan body
                # (MXNET_FUSED_SCAN; None when off or no eligible run)
                from ..fused import _maybe_scan_plan
                cache[is_train] = graph_eval_fn(
                    symbol, is_train, scan=_maybe_scan_plan(symbol))[0]
            gfn = cache[is_train]
            if self.n_rng:
                key = arrays[-1]
                arrays = arrays[:-1]
            else:
                key = jax.random.PRNGKey(0)
            na = len(self.arg_names)
            args, aux = arrays[:na], arrays[na:]
            outs, new_aux = gfn(tuple(args), tuple(aux), key)
            if is_train and new_aux:
                return tuple(outs) + tuple(new_aux)
            return tuple(outs) if len(outs) > 1 else outs[0]

        from ..ops.registry import register_opdef
        from ..compile import graph_hash_of_text
        self.op = register_opdef(OpDef(
            name=f"_cached_op{uid}", fn=op_fn, nin=-1,
            nout=n_out, naux=len(self.aux_names),
            params={}, mode_dependent=True, needs_rng=n_rng > 0,
            # symbol-JSON hash (NOT the process-local uid) keys the
            # unified program cache's disk tier: the same hybridized
            # block in a fresh process loads its compiled executable
            cache_key=graph_hash_of_text(symbol.tojson())))

    def __call__(self, inputs, param_lookup):
        """inputs: list[NDArray]; param_lookup: name -> NDArray."""
        data_map = dict(zip(self.data_names, inputs))
        args = []
        for name in self.arg_names:
            if name in data_map:
                args.append(data_map[name])
            else:
                args.append(param_lookup(name))
        for name in self.aux_names:
            args.append(param_lookup(name))
        return invoke(self.op, args, {})


class HybridBlock(Block):
    """Block with optional trace-to-XLA compilation
    (reference `block.py:672 HybridBlock`)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_graph = None
        self._flags = {}

    def __setattr__(self, name, value):
        super().__setattr__(name, value)
        if isinstance(value, HybridBlock):
            self._clear_cached_op()

    def _clear_cached_op(self):
        self._cached_graph = None

    def hybridize(self, active=True, **kwargs):
        """Activate compiled execution (reference `block.py hybridize`)."""
        self._active = active
        self._flags = kwargs
        self._clear_cached_op()
        super().hybridize(active, **kwargs)

    def cast(self, dtype):
        self._clear_cached_op()
        super().cast(dtype)

    def infer_shape(self, *args):
        self._deferred_infer_shape(*args)

    def _trace_symbol(self, n_inputs):
        """Trace hybrid_forward into a Symbol graph."""
        from .. import symbol as sym_mod
        from ..symbol.symbol import Symbol, Group
        data_syms = [sym_mod.var(f"data{i}" if n_inputs > 1 else "data")
                     for i in range(n_inputs)]
        param_syms = {name: p.var() for name, p in self._reg_params.items()}
        out = self.hybrid_forward(sym_mod, *data_syms, **param_syms)
        if isinstance(out, (list, tuple)):
            out = Group(list(out))
        names = [s.name for s in data_syms]
        return out, names

    def _deferred_infer_shape(self, *args):
        """Infer unknown parameter shapes from input shapes by tracing
        (reference `block.py _deferred_infer_shape` → infer_shape pass)."""
        inputs = [a for a in args if isinstance(a, NDArray)]
        out, names = self._trace_symbol(len(inputs))
        shapes = {n: i.shape for n, i in zip(names, inputs)}
        arg_shapes, _, aux_shapes = out._infer_shape_impl(True, **shapes)
        all_params = {p.name: p for p in self.collect_params().values()}
        inferred = dict(zip(out.list_arguments(), arg_shapes or []))
        inferred.update(dict(zip(out.list_auxiliary_states(),
                                 aux_shapes or [])))
        for name, shape in inferred.items():
            if name in all_params and shape is not None:
                all_params[name].shape = shape

    def _finish_deferred(self, *args):
        from .. import engine as _engine
        with _engine.bulk(64):
            for p in self.collect_params().values():
                if p._deferred_init:
                    try:
                        p._finish_deferred_init()
                    except AssertionError:
                        self._deferred_infer_shape(*args)
                        p._finish_deferred_init()

    def _build_cache(self, *args):
        inputs = [a for a in args if isinstance(a, NDArray)]
        out, names = self._trace_symbol(len(inputs))
        self._cached_graph = _CachedGraph(out, len(inputs), names, self)

    def forward(self, x, *args):
        """Dispatch eager or cached-compiled (reference `block.py:902`)."""
        if isinstance(x, NDArray):
            ctx = x.context
            try:
                params = {name: p.data(ctx)
                          for name, p in self._reg_params.items()}
            except DeferredInitializationError:
                from .. import engine as _engine
                self._deferred_infer_shape(x, *args)
                with _engine.bulk(64):
                    for p in self.collect_params().values():
                        if p._deferred_init:
                            p._finish_deferred_init()
                params = {name: p.data(ctx)
                          for name, p in self._reg_params.items()}

            if self._active:
                return self._call_cached_op(x, *args)
            return self.hybrid_forward(nd, x, *args, **params)
        # symbolic input (SymbolBlock composition)
        from .. import symbol as sym_mod
        params = {name: p.var() for name, p in self._reg_params.items()}
        return self.hybrid_forward(sym_mod, x, *args, **params)

    def _call_cached_op(self, *args):
        inputs = [a for a in args if isinstance(a, NDArray)]
        # finish deferred init for ALL nested params before compiling
        pending = [p for p in self.collect_params().values()
                   if p._data is None]
        if pending:
            from .. import engine as _engine
            self._deferred_infer_shape(*inputs)
            with _engine.bulk(64):
                for p in pending:
                    if p._deferred_init:
                        p._finish_deferred_init()
                    else:
                        p.initialize(ctx=inputs[0].context)
        if self._cached_graph is None:
            self._build_cache(*args)
        cg = self._cached_graph
        ctx = inputs[0].context
        all_params = None

        def lookup(name):
            nonlocal all_params
            if all_params is None:
                all_params = {p.name: p
                              for p in self.collect_params().values()}
            return all_params[name].data(ctx)

        return cg(inputs, lookup)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    def export(self, path, epoch=0):
        """Save symbol + params for deployment (reference `block.py:869`)."""
        if self._cached_graph is None:
            raise MXNetError("Please first call block.hybridize() and then "
                             "run forward with this block at least once "
                             "before calling export.")
        sym = self._cached_graph.symbol
        sym.save(f"{path}-symbol.json")
        arg_names = set(sym.list_arguments())
        aux_names = set(sym.list_auxiliary_states())
        arg_dict = {}
        for param in self.collect_params().values():
            if param.name in arg_names:
                arg_dict[f"arg:{param.name}"] = param._reduce()
            elif param.name in aux_names:
                arg_dict[f"aux:{param.name}"] = param._reduce()
        nd.save("%s-%04d.params" % (path, epoch), arg_dict)


class SymbolBlock(HybridBlock):
    """Wrap an arbitrary Symbol as a Block (reference `block.py:953`)."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix=None, params=params)
        from ..symbol.symbol import Symbol, Group
        if isinstance(outputs, (list, tuple)):
            outputs = Group(list(outputs))
        if isinstance(inputs, Symbol):
            inputs = [inputs]
        self._output_symbol = outputs
        self._input_names = [i.name for i in inputs]
        arg_names = outputs.list_arguments()
        aux_names = outputs.list_auxiliary_states()
        for name in arg_names:
            if name not in self._input_names:
                self._reg_params[name] = self.params.get(
                    name[len(self.params.prefix):] if name.startswith(
                        self.params.prefix) else name,
                    allow_deferred_init=True)
                self._reg_params[name].name = name
                self.params._params[name] = self._reg_params[name]
        for name in aux_names:
            self._reg_params[name] = self.params.get(
                name, grad_req="null", allow_deferred_init=True)
            self._reg_params[name].name = name
            self.params._params[name] = self._reg_params[name]
        self._cached_graph = _CachedGraph(outputs, len(inputs),
                                          self._input_names, self)

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        """Reference `block.py:986 SymbolBlock.imports`."""
        from .. import symbol as sym_mod
        output = sym_mod.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [sym_mod.var(n) for n in input_names]
        ret = SymbolBlock(output, inputs)
        if param_file is not None:
            loaded = nd.load(param_file)
            fixed = {}
            for k, v in loaded.items():
                name = k.split(":", 1)[1] if ":" in k else k
                fixed[name] = v
            for name, param in ret._reg_params.items():
                if name in fixed:
                    param.shape = fixed[name].shape
                    param.initialize(ctx=ctx or [cpu()])
                    param.set_data(fixed[name])
        return ret

    def forward(self, x, *args):
        if isinstance(x, NDArray):
            inputs = [x] + [a for a in args if isinstance(a, NDArray)]
            ctx = x.context
            for p in self.collect_params().values():
                if p._data is None and not p._deferred_init:
                    p.initialize(ctx=ctx)
                elif p._deferred_init:
                    p._finish_deferred_init()

            def lookup(name):
                return self._reg_params[name].data(ctx)

            return self._cached_graph(inputs, lookup)
        raise MXNetError("SymbolBlock requires NDArray inputs")

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError
