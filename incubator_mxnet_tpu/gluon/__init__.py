"""Gluon: the imperative-first API (reference `python/mxnet/gluon/`).

Define-by-run Blocks with optional `hybridize()` trace-to-XLA compilation —
the API the TPU framework centers on (SURVEY.md §2.3).
"""
from .parameter import Parameter, Constant, ParameterDict, \
    DeferredInitializationError
from .block import Block, HybridBlock, SymbolBlock
from .trainer import Trainer
from . import nn
from . import rnn
from . import loss
from . import data
from . import model_zoo
from . import utils
from . import contrib
from .utils import split_and_load
