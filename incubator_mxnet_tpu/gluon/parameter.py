"""Gluon Parameter / ParameterDict (reference `python/mxnet/gluon/parameter.py`).

Parameter holds per-context NDArray copies with deferred shape init; `var()`
exposes it to symbolic tracing (hybridize).  Gradient buffers attach through
the autograd tape (`attach_grad`), exactly as the reference wires
`mark_variables`.
"""
from __future__ import annotations

from ..base import MXNetError
from ..context import Context, cpu, current_context
from .. import initializer as init_mod
from ..initializer import InitDesc
from ..ndarray.ndarray import NDArray
from .. import ndarray as nd

__all__ = ["DeferredInitializationError", "Parameter", "Constant",
           "ParameterDict"]


class DeferredInitializationError(MXNetError):
    """Error for unfinished deferred initialization (reference parameter.py)."""


class Parameter:
    """A Block parameter (reference `parameter.py:Parameter`)."""

    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self._var = None
        self._data = None       # list[NDArray], one per ctx
        self._grad = None
        self._ctx_list = None
        self._deferred_init = ()
        self.name = name
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        if not differentiable:
            grad_req = "null"
        self._grad_req = None
        self.grad_req = grad_req
        self._stype = stype

    def __repr__(self):
        return f"Parameter {self.name} (shape={self.shape}, dtype={self.dtype})"

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        assert req in ("write", "add", "null")
        if not self._differentiable:
            req = "null"
        if self._grad_req == req:
            return
        self._grad_req = req
        if req == "null":
            self._grad = None
            if self._data:
                for d in self._data:
                    d._mark_variable(None, "null")
                    d._requires_grad = False
        elif self._data is not None:
            self._init_grad()

    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        unknown_ok = all(s1 == 0 or s1 == s2
                         for s1, s2 in zip(self._shape, new_shape))
        if not (len(self._shape) == len(new_shape) and unknown_ok):
            raise AssertionError(
                f"Expected shape {new_shape} is incompatible with given shape "
                f"{self._shape}.")
        self._shape = tuple(new_shape)

    def _check_initialized(self, ctx=None):
        if self._data is not None:
            if ctx is not None and ctx not in self._ctx_list:
                raise MXNetError(
                    f"Parameter '{self.name}' was not initialized on context "
                    f"{ctx}. It was only initialized on {self._ctx_list}.")
            return
        if self._deferred_init:
            raise DeferredInitializationError(
                f"Parameter '{self.name}' has not been initialized yet because "
                "initialization was deferred. Actual initialization happens "
                "during the first forward pass. Please pass one batch of data "
                "through the network before accessing Parameters.")
        raise MXNetError(
            f"Parameter '{self.name}' has not been initialized. Note that you "
            "should initialize parameters and create Trainer with "
            "Block.collect_params() instead of Block.params because the later "
            "does not include Parameters of nested child Blocks")

    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        """Reference `parameter.py initialize`."""
        if default_init is None:
            default_init = init_mod.Uniform()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        if init is None:
            init = self.init  # may be None -> pattern-dispatched default_init
        if self._shape is None or any(s == 0 for s in self._shape):
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init, None)
                return
            raise ValueError(
                f"Cannot initialize Parameter '{self.name}' because it has "
                "invalid shape: {self._shape}.")
        self._deferred_init = (init, ctx, default_init, None)
        self._finish_deferred_init()

    def _finish_deferred_init(self):
        if not self._deferred_init:
            return
        init, ctx, default_init, data = self._deferred_init
        self._deferred_init = ()
        assert self._shape is not None and all(s > 0 for s in self._shape), \
            f"Cannot initialize Parameter '{self.name}' because it has " \
            f"invalid shape: {self._shape}."
        if data is None:
            data = nd.zeros(self._shape, dtype=self.dtype, ctx=cpu())
            if isinstance(init, init_mod.Initializer):
                # explicit per-parameter init overrides name-pattern dispatch
                init._init_weight(InitDesc(self.name), data)
            elif isinstance(init, str):
                init_mod.create(init)._init_weight(InitDesc(self.name), data)
            elif callable(init):
                init(InitDesc(self.name), data)
            else:
                # gluon semantics: the default initializer is applied via
                # _init_weight regardless of the parameter name pattern
                # (reference parameter.py passes {'__init__': init} attrs)
                d = init_mod.create(default_init)
                if isinstance(d, init_mod.Initializer):
                    d._init_weight(InitDesc(self.name), data)
                else:
                    d(InitDesc(self.name), data)
        self._init_impl(data, ctx)

    def _init_impl(self, data, ctx_list):
        self._ctx_list = list(ctx_list)
        self._data = [data.copyto(c) for c in self._ctx_list]
        # `data` is a scratch buffer; under bulk staging don't ship it to its
        # (cpu) device at flush — only the per-context copies matter
        from .. import engine as _engine
        _engine.unstage(data)
        self._init_grad()

    def _init_grad(self):
        if self.grad_req == "null":
            self._grad = None
            return
        self._grad = [nd.zeros(d.shape, dtype=d.dtype, ctx=d.context)
                      for d in self._data]
        for d, g in zip(self._data, self._grad):
            d._mark_variable(g, self.grad_req)

    def _reduce(self):
        """Average over contexts (reference `parameter.py _reduce`)."""
        if len(self._data) == 1:
            return self._data[0]
        out = self._data[0].copyto(cpu())
        for d in self._data[1:]:
            out += d.copyto(cpu())
        return out / len(self._data)

    def reset_ctx(self, ctx):
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._data:
            data = self._reduce()
            self._init_impl(data, ctx)
        elif self._deferred_init:
            init, _, default_init, data = self._deferred_init
            self._deferred_init = (init, ctx, default_init, data)
        else:
            raise ValueError(f"Cannot reset context for Parameter '{self.name}' "
                             "because it has not been initialized.")

    def set_data(self, data):
        self.shape = data.shape
        if self._data is None:
            assert self._deferred_init, \
                f"Parameter '{self.name}' has not been initialized"
            init, ctx, default_init, _ = self._deferred_init
            self._deferred_init = (init, ctx, default_init, data)
            return
        for d in self._data:
            src = data._data if isinstance(data, NDArray) else data
            import jax
            d._data = jax.device_put(src.astype(d.dtype), d.context.jax_device)

    def data(self, ctx=None):
        """NDArray on the given context (reference `parameter.py data`)."""
        self._check_initialized(ctx)
        if ctx is None:
            return self._data[0]
        for c, d in zip(self._ctx_list, self._data):
            if c == ctx:
                return d
        raise MXNetError(f"Parameter '{self.name}' not initialized on {ctx}")

    def list_data(self):
        self._check_initialized()
        return list(self._data)

    def grad(self, ctx=None):
        if self._data is not None and self._grad is None:
            raise MXNetError(
                f"Cannot get gradient array for Parameter '{self.name}' "
                "because grad_req='null'")
        self._check_initialized(ctx)
        if ctx is None:
            return self._grad[0]
        for c, g in zip(self._ctx_list, self._grad):
            if c == ctx:
                return g
        raise MXNetError(f"Parameter '{self.name}' not initialized on {ctx}")

    def list_grad(self):
        self._check_initialized()
        if self._grad is None:
            raise MXNetError(f"grad_req='null' for Parameter '{self.name}'")
        return list(self._grad)

    def list_ctx(self):
        if self._data is None:
            if self._deferred_init:
                return self._deferred_init[1]
            raise MXNetError(f"Parameter '{self.name}' has not been initialized")
        return self._ctx_list

    def zero_grad(self):
        if self._grad is None:
            return
        for g in self._grad:
            g._data = g._data * 0

    def var(self):
        """Symbol variable for tracing (reference `parameter.py var`)."""
        from ..symbol import Variable
        if self._var is None:
            self._var = Variable(self.name, shape=self._shape,
                                 dtype=self.dtype, lr_mult=self.lr_mult,
                                 wd_mult=self.wd_mult)
        return self._var

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is None:
            return
        self._data = [d.astype(dtype) for d in self._data]
        self._init_grad()


class Constant(Parameter):
    """Non-trainable constant parameter (reference `parameter.py Constant`)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = nd.array(value)
        self.value = value

        class InitC(init_mod.Initializer):
            def _init_weight(self2, _, arr):
                value.copyto(arr)

        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype, init=InitC())


class ParameterDict:
    """Dict of Parameters with prefix (reference `parameter.py ParameterDict`)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = {}
        self._shared = shared

    def __getitem__(self, key):
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def __repr__(self):
        s = "\n".join(repr(v) for v in self.values())
        return f"ParameterDict '{self._prefix}' (\n{s}\n)"

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    @property
    def prefix(self):
        return self._prefix

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def get(self, name, **kwargs):
        """Create-or-retrieve (reference `parameter.py ParameterDict.get`)."""
        name = self.prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if hasattr(param, k) and getattr(param, k) is not None:
                    existing = getattr(param, k)
                    if k == "shape" and v is not None and existing is not None:
                        # merge unknown dims
                        if len(v) == len(existing):
                            merged = tuple(a if a != 0 else b
                                           for a, b in zip(existing, v))
                            param._shape = merged
                        continue
                else:
                    setattr(param, k, v)
        return param

    def get_constant(self, name, value=None):
        name = self.prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise KeyError(f"No constant named '{name}'.")
            param = Constant(name, value)
            self._params[name] = param
        return param

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise ValueError(f"Cannot update self with other because they "
                                 f"have different Parameters with the same "
                                 f"name '{k}'")
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        if init is None:
            init = init_mod.Uniform()
        # bulk scope: initializers run host-side in numpy; the scope exit
        # performs one batched transfer per device instead of one dispatch
        # per parameter (reference bulk mode, include/mxnet/engine.h:308)
        from .. import engine as _engine
        with _engine.bulk(len(self._params) or 1):
            for _, v in self.items():
                v.initialize(None, ctx, init, force_reinit=force_reinit)

    def zero_grad(self):
        for v in self.values():
            v.zero_grad()

    def reset_ctx(self, ctx):
        for v in self.values():
            v.reset_ctx(ctx)

    def setattr(self, name, value):
        for v in self.values():
            setattr(v, name, value)

    def save(self, filename, strip_prefix=""):
        arg_dict = {}
        for param in self.values():
            weight = param._reduce()
            if not param.name.startswith(strip_prefix):
                raise ValueError(f"Prefix '{strip_prefix}' is to be stripped "
                                 f"before saving, but Parameter's name "
                                 f"'{param.name}' does not start with it")
            arg_dict[param.name[len(strip_prefix):]] = weight
        nd.save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        arg_dict = nd.load(filename)
        arg_dict = {restore_prefix + k: v for k, v in arg_dict.items()}
        if not allow_missing:
            for name in self.keys():
                assert name in arg_dict, \
                    f"Parameter '{name}' is missing in file '{filename}'"
        for name in arg_dict:
            if name not in self._params:
                if not ignore_extra:
                    raise ValueError(
                        f"Parameter '{name}' loaded from file '{filename}' is "
                        "not present in ParameterDict")
                continue
            param = self._params[name]
            if param._data is None and param._deferred_init:
                init, pctx, default_init, _ = param._deferred_init
                param.shape = arg_dict[name].shape
                param._deferred_init = (init, pctx if ctx is None else
                                        ([ctx] if isinstance(ctx, Context)
                                         else ctx), default_init,
                                        arg_dict[name])
                param._finish_deferred_init()
            elif param._data is None:
                param.shape = arg_dict[name].shape
                param.initialize(ctx=ctx or [cpu()])
                param.set_data(arg_dict[name])
            else:
                param.set_data(arg_dict[name])
