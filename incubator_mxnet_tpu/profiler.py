"""Profiler (reference `python/mxnet/profiler.py`, C++ `src/profiler/`).

TPU-native: bridges to the JAX/XLA profiler (trace-viewer output readable in
TensorBoard/Perfetto — the chrome://tracing equivalent of the reference's
`DumpProfile`, `src/profiler/profiler.h:270-304`).  The python API surface
(set_config/set_state/dump, Task/Frame/Counter/Marker custom objects) matches
the reference; custom objects are recorded into the same trace via
`jax.profiler.TraceAnnotation`/host events.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time

from .analysis import locks as _alocks

__all__ = ["set_config", "set_state", "state", "dump", "dumps", "pause",
           "resume", "Task", "Frame", "Counter", "Marker",
           "record_memory", "record_serving", "record_supervisor",
           "record_guardian"]

_config = {"profile_all": False, "profile_symbolic": False,
           "profile_imperative": False, "profile_memory": False,
           "profile_api": False, "filename": "profile.json",
           "aggregate_stats": False}
_state = {"running": False, "dir": None}
# BOUNDED event buffer: a long supervised run with the profiler on must
# never exhaust host memory — past MXNET_PROFILER_MAX_EVENTS the OLDEST
# events drop (the newest window is the one being debugged), counted in
# _dropped and surfaced as the 'profiler.dropped_events' metric
_custom_events = collections.deque()
_dropped = [0]
_cap = [None]     # resolved lazily from config (tests re-point it)
_lock = _alocks.make_lock("profiler")


def _event_cap():
    if _cap[0] is None:
        from . import config as _config
        _cap[0] = max(int(_config.get("MXNET_PROFILER_MAX_EVENTS")), 1)
    return _cap[0]


def set_event_cap(n):
    """Override the in-memory event-buffer cap (tests; None re-reads
    MXNET_PROFILER_MAX_EVENTS on the next emit)."""
    _cap[0] = None if n is None else max(int(n), 1)


def buffer_stats():
    """{"events", "dropped_events", "cap", "running"} — registered as
    the 'profiler' namespace in the obs metrics registry."""
    with _lock:
        return {"events": len(_custom_events),
                "dropped_events": _dropped[0],
                "cap": _event_cap(),
                "running": _state["running"]}


_kvstore_handle = [None]


def set_kvstore_handle(kv):
    """Register the dist kvstore used to forward `profile_process=
    'server'` commands (reference `profiler.py:29 set_kvstore_handle`;
    KVStoreDist registers itself on creation)."""
    _kvstore_handle[0] = kv


def _forward_to_servers(action, **kw):
    kv = _kvstore_handle[0]
    if kv is None or not hasattr(kv, "server_profiler_command"):
        raise RuntimeError(
            "profile_process='server' requires a dist kvstore "
            "(create one before driving the server profiler)")
    kv.server_profiler_command(action, **kw)


def set_config(**kwargs):
    """Reference `profiler.py:33 set_config`."""
    if kwargs.pop("profile_process", "worker") == "server":
        _forward_to_servers("set_config", config=kwargs)
        return
    _config.update(kwargs)


def set_state(state_="stop", profile_process="worker"):
    """'run' starts a JAX profiler trace; 'stop' ends and writes it
    (reference `profiler.py set_state` → `MXSetProcessProfilerState`);
    profile_process='server' drives the dist parameter servers'
    profilers instead."""
    import jax
    if profile_process == "server":
        _forward_to_servers("set_state", state=state_)
        return
    if state_ == "run" and not _state["running"]:
        trace_dir = os.path.splitext(_config["filename"])[0] + "_trace"
        os.makedirs(trace_dir, exist_ok=True)
        try:
            jax.profiler.start_trace(trace_dir)
            _state.update(running=True, dir=trace_dir)
        except Exception:
            _state.update(running=True, dir=None)  # already tracing etc.
    elif state_ == "stop" and _state["running"]:
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        _state.update(running=False)


def state():
    return "run" if _state["running"] else "stop"


def pause(profile_process="worker"):
    set_state("stop", profile_process=profile_process)


def resume(profile_process="worker"):
    set_state("run", profile_process=profile_process)


def dump(finished=True, profile_process="worker"):
    """Write custom-event chrome trace alongside the XLA trace
    (reference `MXDumpProfile`); profile_process='server' makes each
    parameter server write ITS profile file."""
    if profile_process == "server":
        _forward_to_servers("dump")
        return
    events = []
    with _lock:
        for ev in _custom_events:
            events.append(ev)
    with open(_config["filename"], "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)


def dumps(reset=False):
    """Aggregate stats string (reference `MXAggregateProfileStatsPrint`)."""
    lines = ["Profile Statistics:"]
    with _lock:
        by_name = {}
        for ev in _custom_events:
            if ev.get("ph") == "X":
                by_name.setdefault(ev["name"], []).append(ev["dur"])
        for name, durs in sorted(by_name.items()):
            lines.append(f"  {name}: count={len(durs)} "
                         f"total_us={sum(durs):.1f} avg_us={sum(durs)/len(durs):.1f}")
        if reset:
            _custom_events.clear()
    return "\n".join(lines)


def _emit(event):
    cap = _event_cap()
    with _lock:
        _custom_events.append(event)
        while len(_custom_events) > cap:
            # drop-oldest, counted: memory stays bounded and the loss
            # is visible in the scrape plane instead of silent
            _custom_events.popleft()
            _dropped[0] += 1


def _tid():
    """Stable small int for the chrome-trace tid lane (trace viewers
    reject non-int tids; the thread NAME rides in args['thread'])."""
    return threading.get_ident() & 0xFFFF


def _tname():
    return threading.current_thread().name


def _imperative_active():
    """True when eager ops should be timed (reference
    `profile_imperative` config, `MXSetProcessProfilerConfig`)."""
    return _state["running"] and (_config.get("profile_imperative", True)
                                  or _config.get("profile_all", False))


def record_op(name, dur_us):
    """Record one eager operator execution (feeds the per-op aggregate
    table, reference `profiler.cc` ProfileOperator)."""
    _emit({"name": name, "cat": "operator", "ph": "X",
           "dur": float(dur_us), "ts": 0, "pid": 0, "tid": 0})
    if _config.get("profile_memory") or _config.get("profile_all"):
        record_memory(name)


def record_memory(tag="memory", ctx=None):
    """Record a device-memory sample (reference memory profiler:
    `src/profiler/storage_profiler.h` DeviceStorageProfiler events,
    aggregated as `Memory:<device>` counters in DumpProfile).

    The reference hooks every StorageManager alloc/free; XLA owns
    allocation here, so the equivalent observable is the PJRT counter set
    (bytes_in_use / peak_bytes_in_use) sampled at op boundaries when
    `profile_memory` is set, or on demand via this function."""
    from .storage import memory_stats
    stats = memory_stats(ctx)
    if not stats:
        return None
    ev = {"name": f"Memory:{tag}", "cat": "memory", "ph": "C",
          "ts": time.perf_counter() * 1e6, "pid": 0, "tid": 0,
          "args": {"bytes_in_use": int(stats.get("bytes_in_use", 0)),
                   "peak_bytes_in_use":
                       int(stats.get("peak_bytes_in_use", 0))}}
    _emit(ev)
    return ev["args"]


def record_serving(name, dur_us, **args):
    """Record one serving batch execution (serving.metrics feeds this per
    executed bucket) into the chrome trace next to the custom-object
    events.  A no-op unless a profile is running, so the serving hot path
    never accumulates events nobody will dump."""
    if not _state["running"]:
        return
    _emit({"name": name, "cat": "serving", "ph": "X",
           "ts": time.perf_counter() * 1e6 - float(dur_us),
           "dur": float(dur_us), "pid": 0, "tid": _tid(),
           "args": dict(args, thread=_tname())})


def _record_instant(cat, name, **args):
    """One global instant event in the chrome trace with the emitting
    thread's lane — the shared emitter behind the supervisor/guardian/
    fault event lanes.  A no-op unless a profile is running."""
    if not _state["running"]:
        return
    _emit({"name": f"{cat}:{name}", "cat": cat, "ph": "i", "s": "g",
           "ts": time.perf_counter() * 1e6, "pid": 0, "tid": _tid(),
           "args": dict(args, thread=_tname())})


def record_supervisor(event, **args):
    """Record one elastic-supervisor event (host lost, straggler flagged,
    collective watchdog timeout, shrink commit — resilience.supervisor
    feeds this), so pod-level membership churn lines up against the
    training steps it disrupted."""
    _record_instant("supervisor", event, **args)


def record_guardian(event, **args):
    """Record one training-guardian event (skip-batch, rollback,
    quarantine, divergence — resilience.guardian feeds this), so
    numerical-health interventions line up against the training steps
    they protected."""
    _record_instant("guardian", event, **args)


def record_kvstore(event, **args):
    """Record one bucketed-communication event (the collective kvstore
    feeds this per batched push: buckets cut, bytes reduced, overlap
    hits), so the gradient-exchange economy lines up against the train
    steps it served."""
    _record_instant("kvstore", event, **args)


def record_fault(site, kind, **args):
    """Record one fired fault / resilience event (resilience.faults feeds
    this), so chaos-run failure injections line up against the serving
    batches and XLA work they disrupted."""
    _record_instant("fault", site, kind=kind, **args)


class _Named:
    def __init__(self, name):
        self.name = name

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *a):
        self.stop()


class Task(_Named):
    """Reference `profiler.py:257 Task`."""

    def __init__(self, name, domain=None):
        super().__init__(name)
        self._t0 = None
        self._ann = None

    def start(self):
        import jax
        self._t0 = time.perf_counter_ns()
        try:
            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        except Exception:
            self._ann = None

    def stop(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
        if self._t0 is not None:
            dur = (time.perf_counter_ns() - self._t0) / 1000.0
            _emit({"name": self.name, "ph": "X", "cat": "task",
                   "ts": self._t0 / 1000.0, "dur": dur, "pid": 0, "tid": 0})


class Frame(Task):
    """Reference `profiler.py Frame`."""


class Counter:
    """Reference `profiler.py Counter`."""

    def __init__(self, name, domain=None, value=None):
        self.name = name
        self.value = 0
        if value is not None:
            self.set_value(value)

    def set_value(self, value):
        self.value = value
        _emit({"name": self.name, "ph": "C", "ts": time.perf_counter_ns() / 1e3,
               "pid": 0, "args": {self.name: value}})

    def increment(self, delta=1):
        self.set_value(self.value + delta)

    def decrement(self, delta=1):
        self.set_value(self.value - delta)

    def __iadd__(self, v):
        self.increment(v)
        return self

    def __isub__(self, v):
        self.decrement(v)
        return self


class Marker:
    """Reference `profiler.py Marker` (instant event)."""

    def __init__(self, name, domain=None):
        self.name = name

    def mark(self, scope="process"):
        _emit({"name": self.name, "ph": "i", "ts": time.perf_counter_ns() / 1e3,
               "pid": 0, "tid": 0, "s": scope[0]})


def profiler_set_config(mode="symbolic", filename="profile.json"):
    """Deprecated reference API kept for compatibility."""
    set_config(filename=filename)


def profiler_set_state(state_="stop"):
    set_state(state_)


# telemetry plane: the buffer economy under the 'profiler' namespace
from .obs import metrics as _obs_metrics  # noqa: E402

_obs_metrics.register_producer("profiler", buffer_stats)
