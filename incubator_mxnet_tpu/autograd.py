"""Autograd: tape-based reverse-mode differentiation for the eager API.

Re-expression of the reference's imperative autograd
(`src/imperative/imperative.cc` — RecordOp, Backward:270; python surface
`python/mxnet/autograd.py`).  The tape records (op, params, inputs, outputs)
per eager call under `record()`; `backward()` walks it in reverse and gets
each op's input gradients from `jax.vjp` of the registered compute function
(the `FGradient` walk at `imperative.cc:142-162`, with XLA-compiled vjps
instead of hand-written backward kernels).

Under `jit`-compiled paths (CachedOp / symbolic executor) gradients are taken
over the whole compiled graph instead — this tape only serves true eager code.
"""
from __future__ import annotations

import threading

from .base import MXNetError

__all__ = ["record", "pause", "train_mode", "predict_mode", "mark_variables",
           "backward", "grad", "is_recording", "is_training", "set_recording",
           "set_training", "get_symbol", "Function"]

_state = threading.local()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
        _state.tape = []
        _state.scope_depth = 0  # nesting of record()/pause() scopes
    return _state


def is_recording():
    """Reference `autograd.is_recording` (`python/mxnet/autograd.py:32`)."""
    return _st().recording


def is_training():
    return _st().training


def set_recording(is_record):
    st = _st()
    prev, st.recording = st.recording, bool(is_record)
    return prev


def set_training(train_mode_):
    st = _st()
    prev, st.training = st.training, bool(train_mode_)
    return prev


class _RecordingStateScope:
    """Scope guard (reference `autograd.py:_RecordingStateScope`)."""

    def __init__(self, is_record, train_mode_):
        self._enter_is_record = is_record
        self._enter_train_mode = train_mode_
        self._prev_is_record = None
        self._prev_train_mode = None

    def __enter__(self):
        st = _st()
        if self._enter_is_record is not None:
            # a fresh outermost record() starts a new graph: drop stale tape
            # entries from earlier scopes whose backward was never taken
            # (otherwise forward-only record scopes leak entries — and pin
            # their input snapshots — indefinitely)
            if self._enter_is_record and st.scope_depth == 0 and st.tape:
                st.tape = []
            st.scope_depth += 1
            self._prev_is_record = set_recording(self._enter_is_record)
        if self._enter_train_mode is not None:
            self._prev_train_mode = set_training(self._enter_train_mode)

    def __exit__(self, ptype, value, trace):
        if self._enter_is_record is not None:
            _st().scope_depth -= 1
            set_recording(self._prev_is_record)
        if self._enter_train_mode is not None:
            set_training(self._prev_train_mode)


def record(train_mode=True):
    """Start recording ops for backward (reference `autograd.py:122 record`)."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode=False):
    """Stop recording inside an outer `record` scope (reference `autograd.py:146`)."""
    return _RecordingStateScope(False, train_mode)


def train_mode():
    """Force train-mode op behavior without recording (reference `autograd.py:166`)."""
    return _RecordingStateScope(None, True)


def predict_mode():
    """Force predict-mode op behavior (reference `autograd.py:181`)."""
    return _RecordingStateScope(None, False)


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers (reference `autograd.py:197 mark_variables`)."""
    if not isinstance(variables, (list, tuple)):
        variables = [variables]
        gradients = [gradients]
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._mark_variable(g, req)


class TapeEntry:
    __slots__ = ("op", "params", "inputs", "input_values", "outputs", "n_vis")

    def __init__(self, op, params, inputs, input_values, outputs, n_vis):
        self.op = op
        self.params = params
        self.inputs = inputs          # list[NDArray] (weakly held via the entry)
        self.input_values = input_values  # list[jax.Array] snapshot at call time
        self.outputs = outputs        # list[NDArray]
        self.n_vis = n_vis            # visible outputs (excludes aux updates)


def _record_op(op, params, inputs, input_values, outputs, n_vis):
    """Called by dispatch after an eager op executes under record()."""
    _st().tape.append(TapeEntry(op, params, list(inputs), list(input_values),
                                list(outputs), n_vis))


def _grad_opdef(base_name):
    """Get/create the differentiable gradient-op for a base operator.

    ``_grad_of_<op>`` computes the base op's input gradients from
    (inputs..., cotangents...) via jax.vjp — and, being an ordinary
    registered op, is itself differentiable, which is what makes
    ``create_graph=True`` (higher-order grad, reference `autograd.py:270`)
    compose for free under JAX.
    """
    from .ops import registry as _reg
    name = "_grad_of_" + base_name
    op = _reg.maybe_get(name)
    if op is not None:
        return op

    def fn(params, *args):
        import jax
        import jax.numpy as jnp
        base = _reg.get(params["_base"])
        bparams = dict(params["_bparams"])
        n_in = params["_n_in"]
        arrays, cts = args[:n_in], args[n_in:]

        def fwd(*xs):
            out = base.fn(bparams, *xs)
            return out if isinstance(out, tuple) else (out,)

        primals, vjp = jax.vjp(fwd, *arrays)
        cts_p = tuple(cts) + tuple(
            jnp.zeros_like(p) for p in primals[len(cts):])
        return tuple(vjp(cts_p))

    op = _reg.OpDef(name, fn, nin=-1, nout=lambda p: p["_n_in"],
                    params={"_base": base_name, "_bparams": (),
                            "_n_in": _reg.REQUIRED, "_n_ct": 0})
    _reg.register_opdef(op)
    return op


def _compute_gradients_recorded(heads, head_grads, retain_graph):
    """create_graph=True walk: gradients are NDArrays and every vjp is
    re-recorded on the tape, so the returned grads support further backward."""
    import jax.numpy as jnp
    from .ndarray.ndarray import NDArray
    from .ops import registry as _reg

    st = _st()
    tape = st.tape
    grad_map = {}
    prev_rec = set_recording(True)
    try:
        for h, hg in zip(heads, head_grads):
            if hg is None:
                hg = NDArray(jnp.ones(h.shape, dtype=h._data.dtype),
                             ctx=h.context)
            key = id(h)
            grad_map[key] = grad_map[key] + hg if key in grad_map else hg

        visited = set()
        for entry in list(reversed(tape)):
            out_ids = [id(o) for o in entry.outputs]
            if not any(oid in grad_map for oid in out_ids):
                continue
            visited.add(id(entry))
            cts = []
            for o, oid in zip(entry.outputs, out_ids):
                g = grad_map.get(oid)
                cts.append(g if g is not None else
                           NDArray(jnp.zeros(o.shape, dtype=o._data.dtype),
                                   ctx=o.context))
            if isinstance(entry, _FunctionTapeEntry):
                # Function.forward runs under pause(), so tensors it saved
                # for backward are off-tape — second-order grads through the
                # user's backward would be silently wrong; refuse loudly
                raise MXNetError(
                    "create_graph=True cannot differentiate through a custom "
                    "autograd.Function (its forward intermediates are not on "
                    "the tape); express the op with registered operators or "
                    "take first-order gradients only")
            else:
                gop = _grad_opdef(entry.op.name)
                gparams = {"_base": entry.op.name,
                           "_bparams": tuple(sorted(entry.params.items())),
                           "_n_in": len(entry.input_values),
                           "_n_ct": len(cts)}
                in_vals = list(entry.input_values) + [c._data for c in cts]
                results = _reg.eager_call(gop, gparams, in_vals)
                nd_igrads = [NDArray(r) for r in results]
                pad = len(entry.input_values) - len(entry.inputs)
                nd_inputs = list(entry.inputs) + [None] * pad + list(cts)
                _record_op(gop, gparams, nd_inputs, in_vals, nd_igrads,
                           len(nd_igrads))
                for o in nd_igrads:
                    o._requires_grad = True
            for inp, ig in zip(entry.inputs, nd_igrads):
                if inp is None or ig is None:
                    continue
                if not getattr(inp, "_requires_grad", False):
                    continue
                key = id(inp)
                grad_map[key] = grad_map[key] + ig if key in grad_map else ig
    finally:
        set_recording(prev_rec)
    if not retain_graph:
        st.tape = [e for e in st.tape if id(e) not in visited]
    return grad_map


from collections import OrderedDict

_FUSED_BWD_CACHE = OrderedDict()   # tape signature -> jitted replay (LRU)
_FUSED_BWD_CACHE_MAX = 64          # bounds variable-shape workloads
_FUSED_BWD_WARNED = [False]


def _tape_plan(tape, heads, head_grads, wanted_ids):
    """One reverse pass over the tape building a POSITIONAL execution plan
    (no array values captured) plus this call's concrete feed.

    Returns (signature, plan, feed, cts, key_of).  Two backward calls with
    equal signatures walk identically, so the jitted replay compiled for
    the first serves the second — the CachedOp idea applied to the
    autograd tape itself.
    """
    key_of = {}          # id(NDArray object) -> dense key int

    def key(obj):
        k = key_of.get(id(obj))
        if k is None:
            k = len(key_of)
            key_of[id(obj)] = k
        return k

    feed_pos = {}        # id(jax array) -> feed index
    feed = []

    def feed_ix(v):
        p = feed_pos.get(id(v))
        if p is None:
            p = len(feed)
            feed_pos[id(v)] = p
            feed.append(v)
        return p

    head_spec = []
    cts = []
    live = set()
    for h, hg in zip(heads, head_grads):
        hk = key(h)
        live.add(hk)
        if hg is not None:
            head_spec.append((hk, len(cts), None, None))
            cts.append(hg._data)
        else:
            head_spec.append((hk, None, tuple(h.shape),
                              str(h._data.dtype)))

    plan = []
    visited = set()
    for entry in reversed(tape):
        if isinstance(entry, _FunctionTapeEntry):
            out_keys = [key_of.get(id(o)) for o in entry.outputs]
            if any(k in live for k in out_keys if k is not None):
                return None    # user-python backward: not traceable
            continue
        out_keys = [key_of.get(id(o)) for o in entry.outputs]
        if not any(k in live for k in out_keys if k is not None):
            continue
        visited.add(id(entry))
        out_meta = tuple(
            (key(o), tuple(o.shape), str(o._data.dtype))
            for o in entry.outputs)
        in_pos = tuple(feed_ix(v) for v in entry.input_values)
        in_keys = []
        for inp in entry.inputs:
            if inp is None or not getattr(inp, "_requires_grad", False):
                in_keys.append(None)
            else:
                k = key(inp)
                live.add(k)
                in_keys.append(k)
        plan.append((entry.op.name,
                     tuple(sorted(entry.params.items())),
                     in_pos, out_meta, tuple(in_keys)))

    wanted = tuple(sorted(key_of[i] for i in wanted_ids
                          if i in key_of and key_of[i] in live))
    signature = (tuple(head_spec), tuple(plan), wanted)
    return signature, plan, feed, cts, key_of, head_spec, wanted, visited


def _build_fused_backward(head_spec, plan, wanted):
    """Compile the positional tape replay: (feed, cts) -> wanted grads."""
    import jax
    import jax.numpy as jnp
    from .ops import registry as _reg

    def run(feed, cts):
        gm = {}
        for hk, ci, shape, dtype in head_spec:
            g = cts[ci] if ci is not None else jnp.ones(shape, dtype=dtype)
            gm[hk] = gm[hk] + g if hk in gm else g
        for opname, pitems, in_pos, out_meta, in_keys in plan:
            op = _reg.get(opname)
            params = dict(pitems)
            vals = [feed[p] for p in in_pos]

            def fwd(*xs, _op=op, _params=params):
                out = _op.fn(_params, *xs)
                return out if isinstance(out, tuple) else (out,)

            primals, vjp = jax.vjp(fwd, *vals)
            cots = []
            for (k, shape, dtype), p in zip(out_meta, primals):
                g = gm.get(k)
                cots.append(g if g is not None
                            else jnp.zeros(shape, dtype=dtype))
            cots += [jnp.zeros_like(p) for p in primals[len(out_meta):]]
            igrads = vjp(tuple(cots))
            for k, ig in zip(in_keys, igrads):
                if k is None or ig is None:
                    continue
                gm[k] = gm[k] + ig if k in gm else ig
        return tuple(gm[k] for k in wanted)

    return jax.jit(run)


def _compute_gradients_fused(heads, head_grads, retain_graph, wanted_ids):
    """One-dispatch backward: the whole reverse walk as a single jitted
    XLA program per tape structure (the TPU answer to the reference's
    per-op `RunGraph` backward, `src/imperative/imperative.cc:270` — on
    TPU each op dispatch is a host round trip, so the tape compiles).

    Returns dict id -> grad array for `wanted_ids`, or None when the tape
    cannot fuse (custom Function entries).
    """
    st = _st()
    out = _tape_plan(st.tape, heads, head_grads, wanted_ids)
    if out is None:
        return None
    signature, plan, feed, cts, key_of, head_spec, wanted, visited = out
    fn = _FUSED_BWD_CACHE.get(signature)
    if fn is None:
        fn = _build_fused_backward(head_spec, plan, wanted)
        _FUSED_BWD_CACHE[signature] = fn
        while len(_FUSED_BWD_CACHE) > _FUSED_BWD_CACHE_MAX:
            _FUSED_BWD_CACHE.popitem(last=False)
    else:
        _FUSED_BWD_CACHE.move_to_end(signature)
    results = fn(feed, cts)
    by_key = dict(zip(wanted, results))
    grad_map = {}
    for i in wanted_ids:
        k = key_of.get(i)
        if k is not None and k in by_key:
            grad_map[i] = by_key[k]
    if not retain_graph:
        st.tape = [e for e in st.tape if id(e) not in visited]
    return grad_map


def _compute_gradients(heads, head_grads, retain_graph=False,
                       wanted_ids=None):
    """Reverse tape walk; returns dict id(NDArray) -> jax grad array."""
    import os
    import jax.numpy as jnp

    st = _st()
    tape = st.tape
    from . import config as _config
    if wanted_ids is not None and _config.get("MXNET_FUSED_BACKWARD"):
        try:
            fused = _compute_gradients_fused(heads, head_grads,
                                             retain_graph, wanted_ids)
        except Exception as e:
            fused = None
            if not _FUSED_BWD_WARNED[0]:
                _FUSED_BWD_WARNED[0] = True
                import logging
                logging.getLogger(__name__).warning(
                    "fused tape backward unavailable (%s); using the "
                    "per-op walk", str(e)[:200])
        if fused is not None:
            return fused
    grad_map = {}
    for h, hg in zip(heads, head_grads):
        g = hg._data if hg is not None else jnp.ones(h.shape, dtype=h._data.dtype)
        key = id(h)
        grad_map[key] = grad_map[key] + g if key in grad_map else g

    visited = set()
    for entry in reversed(tape):
        out_ids = [id(o) for o in entry.outputs]
        if not any(oid in grad_map for oid in out_ids):
            continue
        visited.add(id(entry))
        cotangents = []
        for o, oid in zip(entry.outputs, out_ids):
            g = grad_map.get(oid)
            cotangents.append(g if g is not None
                              else jnp.zeros(o.shape, dtype=o._data.dtype))
        # aux outputs (e.g. BatchNorm running stats) carry no gradient
        igrads = _function_aware_vjp(entry.op, entry.params, entry.input_values,
                                     cotangents)
        for inp, ig in zip(entry.inputs, igrads):
            if inp is None or ig is None:
                continue
            if not getattr(inp, "_requires_grad", False):
                continue
            key = id(inp)
            grad_map[key] = grad_map[key] + ig if key in grad_map else ig
    if not retain_graph:
        # consume only the subgraph this backward walked; entries feeding
        # other heads (e.g. per-device losses in a DP step, each backward'd
        # in turn — the reference's per-graph semantics) stay live until
        # their own backward or the next outermost record() scope
        st.tape = [e for e in tape if id(e) not in visited]
    return grad_map


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Compute gradients of heads w.r.t. marked variables
    (reference `autograd.py:243 backward` → `Imperative::Backward`)."""
    if not isinstance(heads, (list, tuple)):
        heads = [heads]
        head_grads = [head_grads] if head_grads is not None else None
    if head_grads is None:
        head_grads = [None] * len(heads)

    # loud failure instead of silent zero-grads: a head that is neither on
    # the current tape nor a grad-attached leaf was recorded in an earlier
    # record() scope whose graph has been discarded
    st = _st()
    tape_out_ids = {id(o) for e in st.tape for o in e.outputs}
    for h in heads:
        if getattr(h, "_requires_grad", False) and id(h) not in tape_out_ids \
                and h._grad is None:
            raise MXNetError(
                "backward() head is not on the current autograd tape: it was "
                "recorded in an earlier record() scope whose graph was "
                "discarded when a new outermost record() scope started "
                "(tape-based autograd keeps one graph); call backward before "
                "opening the next record scope")

    # collect marked variables reachable on the tape
    marked = []
    seen = set()
    for entry in st.tape:
        for inp in entry.inputs:
            if inp is not None and getattr(inp, "_grad_req", None) not in (None, "null") \
                    and id(inp) not in seen:
                seen.add(id(inp))
                marked.append(inp)
    for h in heads:
        if getattr(h, "_grad_req", None) not in (None, "null") and id(h) not in seen:
            seen.add(id(h))
            marked.append(h)

    grad_map = _compute_gradients(heads, head_grads, retain_graph,
                                  wanted_ids={id(v) for v in marked})

    for v in marked:
        g = grad_map.get(id(v))
        if g is None:
            continue
        if v._grad is None:
            continue
        if v._grad_req == "add":
            v._grad._data = v._grad._data + g
        else:  # write
            v._grad._data = g.astype(v._grad._data.dtype)


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False,
         train_mode=True):
    """Return gradients as new arrays instead of writing `.grad`
    (reference `autograd.py:270 grad`)."""
    if not isinstance(heads, (list, tuple)):
        heads = [heads]
    single = not isinstance(variables, (list, tuple))
    if single:
        variables = [variables]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif not isinstance(head_grads, (list, tuple)):
        head_grads = [head_grads]
    retain = bool(retain_graph) if retain_graph is not None else create_graph
    from .ndarray.ndarray import NDArray
    if create_graph:
        grad_map = _compute_gradients_recorded(heads, head_grads, retain)
    else:
        grad_map = _compute_gradients(heads, head_grads, retain,
                                      wanted_ids={id(v) for v in variables})
    out = []
    for v in variables:
        g = grad_map.get(id(v))
        if g is None:
            raise MXNetError("Some variables are not used by or not "
                             "reachable from the heads")
        # create_graph returns the tape-recorded NDArray itself so later
        # backward passes can differentiate through it
        out.append(g if isinstance(g, NDArray) else NDArray(g, ctx=v.context))
    return out[0] if single else out


def get_symbol(x):
    """Trace the recorded computation of x into a Symbol.

    The reference rebuilds a Symbol from tape nodes
    (`MXAutogradGetSymbol`).  Supported for tape-recorded arrays.
    """
    raise MXNetError("autograd.get_symbol: use hybridize()/CachedOp tracing instead")


class Function:
    """Customizable differentiable function (reference `autograd.py:363 Function`).

    Subclass and override ``forward`` and ``backward``.  The pair is recorded
    on the tape as a single op whose vjp calls the user's ``backward``.
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *args):
        self._saved = args

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray
        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (list, tuple))
        outs = [outputs] if single else list(outputs)
        if is_recording() and any(getattr(i, "_requires_grad", False) for i in inputs):
            entry = _FunctionTapeEntry(self, list(inputs), outs)
            _st().tape.append(entry)
            for o in outs:
                o._requires_grad = True
        return outputs


class _FunctionTapeEntry(TapeEntry):
    """Tape entry whose vjp is the user Function.backward."""

    def __init__(self, func, inputs, outputs):
        self.func = func
        self.inputs = inputs
        self.input_values = [i._data for i in inputs]
        self.outputs = outputs
        self.n_vis = len(outputs)
        self.params = {}

    @property
    def op(self):
        return self  # duck-type: registry.vjp_call is bypassed via _FunctionOp

# patch _compute_gradients to understand Function entries
_orig_vjp_call = None


def _function_aware_vjp(op, params, input_values, cotangents):
    from .ops import registry as _reg
    if isinstance(op, _FunctionTapeEntry):
        from .ndarray.ndarray import NDArray
        cts = [NDArray(c) for c in cotangents]
        with pause():
            igrads = op.func.backward(*cts)
        if not isinstance(igrads, (list, tuple)):
            igrads = [igrads]
        return [g._data if g is not None else None for g in igrads]
    return _reg.vjp_call(op, params, input_values, cotangents)
