"""Server-process bootstrap (reference `python/mxnet/kvstore_server.py`).

The reference blocks inside `KVStoreServer.run()` when DMLC_ROLE=server;
the same surface is provided over the dist parameter server.  Normal
usage never touches this module — `kvstore.create('dist_*')` already
becomes the server in a server-role process.
"""
from __future__ import annotations

import os

from .base import MXNetError

__all__ = ["KVStoreServer"]


class KVStoreServer:
    """Reference `kvstore_server.py:KVStoreServer`."""

    def __init__(self, kvstore=None):
        self.kvstore = kvstore
        self.init_logging = False

    def run(self):
        """Serve until every worker has sent its stop command
        (reference `KVStoreServer.run:64`)."""
        if os.environ.get("DMLC_ROLE") not in ("server", None):
            raise MXNetError("KVStoreServer.run: DMLC_ROLE is not 'server'")
        from .dist.server import ParameterServer
        ParameterServer(
            host=os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1"),
            port=int(os.environ.get("DMLC_PS_ROOT_PORT", 9091)),
        ).serve_forever()


def _init_kvstore_server_module():
    """Reference module-level hook: server-role processes never return."""
    if os.environ.get("DMLC_ROLE") == "server":
        import sys
        KVStoreServer().run()
        sys.exit(0)
