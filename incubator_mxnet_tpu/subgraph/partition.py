"""Graph partitioning (reference `src/operator/subgraph/partition_graph.cc`).

Walks the Symbol DAG, asks the property for fusable chains, and rebuilds
the graph with each chain contracted into one fused-op node.  A chain is
only contracted when its interior nodes have no consumers outside the
chain (the convexity condition `partition_graph.cc` enforces generally).
"""
from __future__ import annotations

from ..base import MXNetError
from ..symbol.symbol import Symbol, _Node
from .subgraph_property import get_subgraph_property


def partition_graph(symbol, prop_or_name):
    prop = (get_subgraph_property(prop_or_name)
            if isinstance(prop_or_name, str) else prop_or_name)
    topo = symbol._topo()

    # consumer counts for the convexity check
    n_consumers = {}
    for node in topo:
        for src, _ in node.inputs:
            n_consumers[id(src)] = n_consumers.get(id(src), 0) + 1
    for node, _ in symbol._entries:
        n_consumers[id(node)] = n_consumers.get(id(node), 0) + 1

    def get_input(node, i=0):
        return node.inputs[i][0] if node.inputs else None

    # choose chains greedily in topo order; a node joins at most one chain
    in_chain = {}
    chains = []
    for node in reversed(topo):          # prefer chains ending latest
        if node.is_variable or id(node) in in_chain:
            continue
        chain = prop.match_chain(node, get_input)
        if not chain:
            continue
        if any(id(c) in in_chain for c in chain):
            continue
        # interior nodes must feed only the next chain node
        ok = all(n_consumers.get(id(c), 0) == 1 for c in chain[:-1])
        if not ok:
            continue
        for c in chain:
            in_chain[id(c)] = len(chains)
        chains.append(chain)

    if not chains:
        return symbol

    # rebuild bottom-up
    memo = {}

    def build(node):
        if id(node) in memo:
            return memo[id(node)]
        if node.is_variable:
            memo[id(node)] = node
            return node
        cidx = in_chain.get(id(node))
        if cidx is not None and node is chains[cidx][-1]:
            chain = chains[cidx]
            op, params, ext_inputs = prop.create_fused_op(chain)
            new_inputs = [(build(src), oi) for src, oi in ext_inputs]
            fused = _Node(op, f"{chain[-1].name}_{prop.name.lower()}",
                          dict(params), new_inputs)
            memo[id(node)] = fused
            return fused
        if cidx is not None:
            raise MXNetError("internal: interior chain node reached "
                             "directly — chain not convex")
        new = _Node(node.op, node.name, dict(node.attrs),
                    [(build(src), oi) for src, oi in node.inputs])
        new._extra_attrs = dict(node._extra_attrs)
        memo[id(node)] = new
        return new

    entries = [(build(n), i) for n, i in symbol._entries]
    return Symbol(entries)


def external_inputs(chain):
    """The fused node's inputs: every (producer, out_idx) feeding the chain
    from outside, first occurrence order."""
    member = {id(c) for c in chain}
    out = []
    seen = set()
    for node in chain:
        for src, oi in node.inputs:
            if id(src) in member:
                continue
            key = (id(src), oi)
            if key not in seen:
                seen.add(key)
                out.append((src, oi))
    return out
