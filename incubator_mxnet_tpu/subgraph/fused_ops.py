"""Default Pallas-backed subgraph backend: FullyConnected(+bias)+ReLU.

The fused kernel runs the matmul on the MXU with the bias add and ReLU
applied in VMEM before the tile is written back — the epilogue fusion XLA
usually does on its own, expressed by hand to prove the escape hatch
works end-to-end (graph partition -> custom kernel inside the jitted
program -> custom VJP for training).  Off-TPU the same kernel executes in
Pallas interpret mode, so tests run on the CPU mesh.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..ops import registry as _reg
from .subgraph_property import SubgraphProperty, register_subgraph_property
from .partition import external_inputs


def _on_tpu():
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _fc_relu_pallas(x, w, b):
    """relu(x @ w.T + b) via one Pallas kernel."""
    from jax.experimental import pallas as pl

    m, k = x.shape
    n = w.shape[0]

    def kernel(x_ref, w_ref, b_ref, o_ref):
        acc = jnp.dot(x_ref[:], w_ref[:].T,
                      preferred_element_type=jnp.float32)
        o_ref[:] = jnp.maximum(acc + b_ref[:], 0.0).astype(o_ref.dtype)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        interpret=not _on_tpu(),
    )(x, w, b)


@functools.lru_cache(maxsize=1)
def _fused_fc_relu_fn():
    @jax.custom_vjp
    def fused(x, w, b):
        return _fc_relu_pallas(x, w, b)

    def fwd(x, w, b):
        y = fused(x, w, b)
        return y, (x, w, y)

    def bwd(res, g):
        x, w, y = res
        g = jnp.where(y > 0, g, 0.0)
        return g @ w, g.T @ x, jnp.sum(g, axis=0)

    fused.defvjp(fwd, bwd)
    return fused


def _compute(params, x, w, b):
    x2 = x.reshape(x.shape[0], -1) if params["flatten"] and x.ndim > 2 else x
    return _fused_fc_relu_fn()(x2, w, b)


_OP = _reg.OpDef(
    "_sg_pallas_fc_relu", _compute, nin=3,
    params={"num_hidden": _reg.REQUIRED, "flatten": True},
    input_names=["data", "weight", "bias"],
    doc="Fused FC+ReLU Pallas kernel (subgraph backend TPU_PALLAS)")
_reg.register_opdef(_OP)


class PallasFCReluProperty(SubgraphProperty):
    """Matches Activation(relu)(FullyConnected(data, w, b)) chains."""

    name = "TPU_PALLAS"

    def match_chain(self, node, get_input):
        if node.is_variable or node.op.name != "Activation":
            return None
        if node.attrs.get("act_type") != "relu":
            return None
        prod = get_input(node)
        if prod is None or prod.is_variable:
            return None
        if prod.op.name != "FullyConnected":
            return None
        if prod.attrs.get("no_bias"):
            return None                      # kernel variant expects bias
        if not prod.attrs.get("flatten", True):
            # flatten=False admits N-D inputs the 2-D kernel can't take;
            # leave those to XLA
            return None
        return [prod, node]

    def create_fused_op(self, nodes):
        fc = nodes[0]
        params = {"num_hidden": fc.attrs["num_hidden"],
                  "flatten": fc.attrs.get("flatten", True)}
        return _OP, params, external_inputs(nodes)


register_subgraph_property(PallasFCReluProperty())
