"""Subgraph partition framework (reference `src/operator/subgraph/`:
`subgraph_property.h`, `partition_graph.cc:767`).

Pluggable backends mark regions of a Symbol graph and replace each with a
single fused operator — the escape hatch for custom kernels the compiler
will not produce on its own.  On TPU the payoff is a hand-written Pallas
kernel occupying an op slot inside an otherwise XLA-compiled graph
(`fused_ops.py` ships a fused FullyConnected+ReLU as the working
example, the role MKLDNN/TensorRT properties play in the reference).

Usage:
    partitioned = subgraph.partition_graph(sym, "TPU_PALLAS")
or set MXNET_SUBGRAPH_BACKEND=TPU_PALLAS to partition inside
`simple_bind` (the reference's env-var behavior, `build_subgraph.cc`).
"""
from .subgraph_property import (SubgraphProperty, register_subgraph_property,
                                get_subgraph_property, list_backends)
from .partition import partition_graph
from . import fused_ops  # registers the default TPU_PALLAS backend

__all__ = ["SubgraphProperty", "register_subgraph_property",
           "get_subgraph_property", "list_backends", "partition_graph"]
