"""SubgraphProperty: what to match and what to replace it with
(reference `src/operator/subgraph/subgraph_property.h`)."""
from __future__ import annotations

from ..base import MXNetError

_BACKENDS = {}


class SubgraphProperty:
    """A pluggable partition backend.

    Subclasses override:
    * `match_chain(node, get_input)` — given a candidate END node of a
      chain (and a callback returning the producer of its i-th input),
      return the list of chain nodes [first..last] to fuse, or None.
      Chain fusion covers the practically useful cases (conv+bn+relu,
      fc+relu, quantize chains) without the full convex-cut machinery of
      `partition_graph.cc`; properties needing richer selection can
      override `select` wholesale.
    * `create_fused_op(nodes)` — return (registered OpDef, params dict,
      external inputs) computing the fused chain; the fn sees the chain's
      ORIGINAL external inputs in first-occurrence order.
    """

    name = "base"

    def match_chain(self, node, get_input):
        return None

    def create_fused_op(self, nodes):
        raise NotImplementedError


def register_subgraph_property(prop):
    """Register a backend instance (reference
    `MXNET_REGISTER_SUBGRAPH_PROPERTY`)."""
    _BACKENDS[prop.name] = prop
    return prop


def get_subgraph_property(name):
    try:
        return _BACKENDS[name]
    except KeyError:
        raise MXNetError(
            f"subgraph backend {name!r} is not registered; available: "
            f"{sorted(_BACKENDS)}") from None


def list_backends():
    return sorted(_BACKENDS)
