"""Base utilities: error type, dtype tables, param coercion.

TPU-native re-expression of the reference's `python/mxnet/base.py` (ctypes plumbing,
`MXNetError`) and the dmlc parameter coercion rules (`dmlc::Parameter`,
reference `include/mxnet/op_attr_types.h`).  There is no C ABI boundary here: the
"backend" is JAX/XLA, so `base` only carries the pieces that are API surface —
the exception type, dtype name tables, and string->python coercion used for
MXNet-style stringly-typed op parameters.
"""
from __future__ import annotations

import ast
import numpy as _np

__all__ = ["MXNetError", "string_types", "numeric_types", "integer_types",
           "dtype_np_to_mx", "dtype_mx_to_np", "_Null"]


class MXNetError(RuntimeError):
    """Error raised by the framework (reference: `python/mxnet/base.py` MXNetError)."""


class _NullType:
    """Placeholder for missing optional op arguments (reference `base.py _NullType`)."""

    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "_Null"

    def __bool__(self):
        return False


_Null = _NullType()

string_types = (str,)
integer_types = (int, _np.integer)
numeric_types = (float, int, _np.generic)

# dtype code table mirrors reference `python/mxnet/base.py` / mshadow type codes,
# extended with bfloat16 which is the TPU-native compute dtype.
_DTYPE_NAMES = [
    "float32", "float64", "float16", "uint8", "int32", "int8", "int64",
    "bool", "uint16", "uint32", "uint64", "bfloat16",
]


def _np_dtype(name):
    if name == "bfloat16":
        import ml_dtypes
        return _np.dtype(ml_dtypes.bfloat16)
    return _np.dtype(name)


dtype_mx_to_np = {i: _np_dtype(n) for i, n in enumerate(_DTYPE_NAMES)}
dtype_np_to_mx = {v: k for k, v in dtype_mx_to_np.items()}


def np_dtype(dtype):
    """Normalize a dtype-ish (str, np.dtype, python type) to np.dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        return _np_dtype(dtype)
    return _np.dtype(dtype)


def dtype_name(dtype):
    """Canonical string name for a dtype."""
    d = np_dtype(dtype)
    name = d.name
    if name == "void16":  # ml_dtypes.bfloat16 on some numpy versions
        return "bfloat16"
    return name


def py_literal(value):
    """Coerce an MXNet stringly-typed parameter value to a Python value.

    The reference reflects `dmlc::Parameter` structs into Python with string
    round-tripping ("(2, 2)", "True", "1e-3"); we accept both real Python
    values and their string forms.
    """
    if not isinstance(value, str):
        return value
    s = value.strip()
    low = s.lower()
    if low in ("true", "false"):
        return low == "true"
    if low == "none":
        return None
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        return s
