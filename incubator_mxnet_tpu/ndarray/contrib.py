"""`mx.nd.contrib` namespace (reference `python/mxnet/ndarray/contrib.py`).

Generated `_contrib_*` ops are exposed without the prefix; plus the imperative
control-flow helpers `foreach` / `while_loop` / `cond`
(reference `src/operator/control_flow.cc:1255-1423` — in eager mode these are
python loops, matching the reference's imperative fallback; under hybridize
they trace to `lax.scan`/`while_loop`/`cond`).
"""
from __future__ import annotations

import sys as _sys

from .ndarray import NDArray, invoke
from ..ops import registry as _reg

_this = _sys.modules[__name__]
for _name in _reg.list_ops():
    if _name.startswith("_contrib_"):
        _op = _reg.get(_name)

        def _make(op):
            def fn(*args, **kwargs):
                out = kwargs.pop("out", None)
                return invoke(op, list(args), kwargs, out=out)
            fn.__name__ = op.name[len("_contrib_"):]
            return fn

        setattr(_this, _name[len("_contrib_"):], _make(_op))


def foreach(body, data, init_states):
    """Imperative foreach (reference control_flow.cc _foreach)."""
    states = init_states
    outputs = []
    length = data[0].shape[0] if isinstance(data, (list, tuple)) else data.shape[0]
    for i in range(length):
        if isinstance(data, (list, tuple)):
            eles = [d[i] for d in data]
        else:
            eles = data[i]
        outs, states = body(eles, states)
        outputs.append(outs)
    from . import ndarray as _nd
    if isinstance(outputs[0], (list, tuple)):
        stacked = [
            invoke(_reg.get("stack"), [o[j] for o in outputs],
                   {"num_args": len(outputs), "axis": 0})
            for j in range(len(outputs[0]))]
        return stacked, states
    stacked = invoke(_reg.get("stack"), outputs,
                     {"num_args": len(outputs), "axis": 0})
    return stacked, states


def while_loop(cond, func, loop_vars, max_iterations=None):
    """Imperative while_loop (reference control_flow.cc _while_loop)."""
    steps = 0
    outputs = []
    vars_ = list(loop_vars)
    while bool(cond(*vars_)):
        outs, vars_ = func(*vars_)
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        outputs.append(outs)
        steps += 1
        if max_iterations is not None and steps >= max_iterations:
            break
    if outputs:
        stacked = [invoke(_reg.get("stack"), [o[j] for o in outputs],
                          {"num_args": len(outputs), "axis": 0})
                   for j in range(len(outputs[0]))]
    else:
        stacked = []
    return stacked, vars_


def cond(pred, then_func, else_func):
    """Imperative cond (reference control_flow.cc _cond)."""
    return then_func() if bool(pred) else else_func()


def isinf(data):
    import jax.numpy as jnp
    return NDArray(jnp.isinf(data._data).astype("float32"), ctx=data.context)


def isnan(data):
    import jax.numpy as jnp
    return NDArray(jnp.isnan(data._data).astype("float32"), ctx=data.context)


def isfinite(data):
    import jax.numpy as jnp
    return NDArray(jnp.isfinite(data._data).astype("float32"), ctx=data.context)
