"""NDArray: the async n-dim array bound to a device context.

TPU-native re-expression of the reference NDArray
(`include/mxnet/ndarray.h:61-82`, `src/ndarray/ndarray.cc`,
python surface `python/mxnet/ndarray/ndarray.py`):

* the buffer is a `jax.Array` committed to the context's PJRT device — HBM
  for `mx.tpu()`, host memory for `mx.cpu()` (replaces Chunk + Storage);
* asynchrony: JAX dispatch is async; `wait_to_read()` blocks like the
  reference's `WaitToRead` (PJRT buffer semantics give per-buffer ordering,
  replacing engine read/write vars);
* every operator application goes through `invoke()` below — the equivalent
  of `MXImperativeInvokeEx` → `Imperative::Invoke` (`src/c_api/c_api_ndarray.cc:43-143`,
  `src/imperative/imperative.cc:87`): canonicalize static attrs, fetch the
  jit-cached XLA executable, run, wrap outputs, record on the autograd tape.

Views note (documented divergence): reference basic-slice views alias the
Chunk; here views are functional copies — `__setitem__` on the *same* NDArray
object updates it in place, but writes through a separate view object do not
propagate to the base.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError, np_dtype, dtype_name
from ..context import Context, current_context, cpu
from .. import engine as _engine
from .. import autograd as _autograd
from ..analysis import hostsync as _hostsync
from ..ops import registry as _reg


def _raise_use_after_donation(jarr, exc):
    """Translate a read of a donation-deleted buffer into an MXNetError
    naming the owning parameter (analysis.donation); no-op — and free —
    when the buffer is alive (only ever called from exception handlers)."""
    from ..analysis import donation as _donation
    msg = _donation.explain(jarr)
    if msg is not None:
        raise MXNetError(msg) from exc

__all__ = ["NDArray", "invoke", "array", "zeros", "ones", "full", "empty",
           "arange", "eye", "linspace", "concatenate", "moveaxis", "waitall",
           "imperative_invoke"]


class NDArray:
    __slots__ = ("_data", "_ctx", "_grad", "_grad_req", "_requires_grad",
                 "_stype", "_deferred_init", "__weakref__")

    def __init__(self, data, ctx=None, stype="default"):
        self._data = data
        self._ctx = ctx if ctx is not None else _infer_ctx(data)
        self._grad = None
        self._grad_req = None
        self._requires_grad = False
        self._stype = stype

    # -- basic properties ----------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return _np.dtype(self._data.dtype)

    @property
    def size(self):
        return int(self._data.size)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def context(self):
        return self._ctx

    ctx = context

    @property
    def stype(self):
        return self._stype

    @property
    def grad(self):
        return self._grad

    @property
    def handle(self):
        """Reference keeps a ctypes handle; here the jax.Array is the handle."""
        return self._data

    def __repr__(self):
        try:
            arr = self.asnumpy()
        except Exception as e:  # deferred async error surfaces here, like the ref
            return f"<NDArray {self.shape} @{self._ctx} (error: {e})>"
        return f"{arr}\n<NDArray {'x'.join(map(str, self.shape))} @{self._ctx}>"

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __bool__(self):
        if self.size != 1:
            raise ValueError("The truth value of an NDArray with multiple "
                             "elements is ambiguous.")
        return bool(self.asnumpy().item())

    def __float__(self):
        return float(self.asnumpy().item())

    def __int__(self):
        return int(self.asnumpy().item())

    def __index__(self):
        return int(self)

    # -- sync / conversion ---------------------------------------------------
    def wait_to_read(self):
        """Block until the value is computed (reference `NDArray::WaitToRead`)."""
        if _hostsync._active:
            _hostsync.note("wait_to_read")
        try:
            _engine.wait_to_read(self._data)
        except Exception as e:
            _raise_use_after_donation(self._data, e)
            raise

    def asnumpy(self):
        """Copy to a numpy array, blocking (reference `ndarray.py asnumpy`)."""
        if _hostsync._active:
            _hostsync.note("asnumpy")
        try:
            return _np.asarray(self._data)
        except Exception as e:
            _raise_use_after_donation(self._data, e)
            raise

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(-1)[0]

    def item(self):
        return self.asnumpy().item()

    def astype(self, dtype, copy=True):
        d = np_dtype(dtype)
        if not copy and self.dtype == d:
            return self
        if isinstance(self._data, _np.ndarray) and _engine.bulk_active():
            # bulk mode: host-staged value casts on the host; the engine
            # flush batches the eventual transfer (one dispatch per op
            # would defeat bulk init/state creation)
            out = NDArray(self._data.astype(d), ctx=self._ctx)
            _engine.stage(out)
            return out
        return _apply_op("Cast", [self], {"dtype": dtype_name(d)})

    def copy(self):
        if isinstance(self._data, _np.ndarray) and _engine.bulk_active():
            out = NDArray(self._data.copy(), ctx=self._ctx)
            _engine.stage(out)
            return out
        return _apply_op("_copy", [self], {})

    def copyto(self, other):
        """Cross-device copy (reference `CopyFromTo`, `src/ndarray/ndarray.cc:1147`)."""
        import jax
        if isinstance(other, Context):
            if isinstance(self._data, _np.ndarray) and _engine.bulk_active():
                # bulk mode: keep host-staged, retarget the context; the
                # engine flush performs one batched transfer per device
                out = NDArray(self._data, ctx=other)
                _engine.stage(out)
                return out
            out = NDArray(jax.device_put(self._data, other.jax_device), ctx=other)
            return out
        if isinstance(other, NDArray):
            other._set_data(jax.device_put(self._data.astype(other.dtype),
                                           other._ctx.jax_device))
            return other
        raise TypeError("copyto target must be NDArray or Context")

    def as_in_context(self, context):
        if context == self._ctx:
            return self
        return self.copyto(context)

    as_in_ctx = as_in_context

    def as_nd_ndarray(self):
        return self

    def tostype(self, stype):
        if stype == "default":
            return self
        from . import sparse as _sparse
        return _sparse.cast_storage(self, stype)

    # -- autograd ------------------------------------------------------------
    def attach_grad(self, grad_req="write", stype=None):
        """Allocate a gradient buffer (reference `ndarray.py attach_grad`)."""
        import jax.numpy as jnp
        g = NDArray(jnp.zeros(self.shape, dtype=self._data.dtype), ctx=self._ctx)
        self._mark_variable(g, grad_req)

    def _mark_variable(self, grad_nd, grad_req):
        self._grad = grad_nd
        self._grad_req = grad_req
        self._requires_grad = grad_req != "null"

    def detach(self):
        out = NDArray(self._data, ctx=self._ctx)
        return out

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        _autograd.backward([self], [out_grad], retain_graph=retain_graph,
                           train_mode=train_mode)

    # -- in-place data replacement (engine write-dependency analogue) --------
    def _set_data(self, jarr):
        if _autograd.is_recording() and self._requires_grad:
            raise MXNetError("In-place write to an array that requires grad "
                             "while recording (reference raises the same)")
        self._data = jarr

    # -- shape ops -----------------------------------------------------------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        reverse = kwargs.get("reverse", False)
        return _apply_op("Reshape", [self], {"shape": shape, "reverse": reverse})

    def reshape_like(self, other):
        return self.reshape(other.shape)

    @property
    def T(self):
        return _apply_op("transpose", [self], {"axes": ()})

    # -- indexing ------------------------------------------------------------
    def __getitem__(self, key):
        _check_bool_index(key)
        if isinstance(key, NDArray):
            return _apply_op("_index_nd", [self, key], {})
        if isinstance(key, _np.ndarray) and key.dtype != _np.bool_:
            return _apply_op("_index_nd", [self, array(key, ctx=self._ctx,
                                                       dtype="int32")], {})
        if _is_basic_index(key):
            return _apply_op("_index", [self], {"key": key})
        # mixed advanced indexing: functional fallback (not recorded on tape)
        jkey = _convert_index(key)
        return NDArray(self._data[jkey], ctx=self._ctx)

    def __setitem__(self, key, value):
        import jax
        import jax.numpy as jnp
        if isinstance(self._data, _np.ndarray):  # host-staged buffer
            _engine.unstage(self)
            self._data = jax.device_put(self._data, self._ctx.jax_device)
        if isinstance(value, NDArray):
            value = value._data
        value = jnp.asarray(value, dtype=self._data.dtype)
        if key is None or (isinstance(key, slice) and key == slice(None)):
            self._set_data(jnp.broadcast_to(value, self.shape) + 0)
            return
        jkey = _convert_index(key)
        self._set_data(self._data.at[jkey].set(value))

    # -- arithmetic operators ------------------------------------------------
    def __add__(self, other):
        return _binary(self, other, "broadcast_add", "_plus_scalar")

    def __radd__(self, other):
        return self.__add__(other)

    def __iadd__(self, other):
        out = self.__add__(other)
        self._set_data(out._data.astype(self._data.dtype))
        return self

    def __sub__(self, other):
        return _binary(self, other, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, other):
        return _binary(self, other, None, "_rminus_scalar")

    def __isub__(self, other):
        out = self.__sub__(other)
        self._set_data(out._data.astype(self._data.dtype))
        return self

    def __mul__(self, other):
        return _binary(self, other, "broadcast_mul", "_mul_scalar")

    def __rmul__(self, other):
        return self.__mul__(other)

    def __imul__(self, other):
        out = self.__mul__(other)
        self._set_data(out._data.astype(self._data.dtype))
        return self

    def __truediv__(self, other):
        return _binary(self, other, "broadcast_div", "_div_scalar")

    def __rtruediv__(self, other):
        return _binary(self, other, None, "_rdiv_scalar")

    def __itruediv__(self, other):
        out = self.__truediv__(other)
        self._set_data(out._data.astype(self._data.dtype))
        return self

    def __mod__(self, other):
        return _binary(self, other, "broadcast_mod", "_mod_scalar")

    def __rmod__(self, other):
        return _binary(self, other, None, "_rmod_scalar")

    def __pow__(self, other):
        return _binary(self, other, "broadcast_power", "_power_scalar")

    def __rpow__(self, other):
        return _binary(self, other, None, "_rpower_scalar")

    def __neg__(self):
        return _apply_op("negative", [self], {})

    def __abs__(self):
        return _apply_op("abs", [self], {})

    def __eq__(self, other):
        return _binary(self, other, "broadcast_equal", "_equal_scalar")

    def __ne__(self, other):
        return _binary(self, other, "broadcast_not_equal", "_not_equal_scalar")

    def __gt__(self, other):
        return _binary(self, other, "broadcast_greater", "_greater_scalar")

    def __ge__(self, other):
        return _binary(self, other, "broadcast_greater_equal", "_greater_equal_scalar")

    def __lt__(self, other):
        return _binary(self, other, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, other):
        return _binary(self, other, "broadcast_lesser_equal", "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    def __matmul__(self, other):
        return _apply_op("dot", [self, other], {})

    def __getstate__(self):
        return {"data": self.asnumpy(), "ctx": str(self._ctx), "stype": self._stype}

    def __setstate__(self, state):
        import jax.numpy as jnp
        self._data = jnp.asarray(state["data"])
        dt, did = state["ctx"].split("(")
        self._ctx = Context(dt, int(did.rstrip(")")))
        self._grad = None
        self._grad_req = None
        self._requires_grad = False
        self._stype = state.get("stype", "default")


def _infer_ctx(jarr):
    try:
        dev = next(iter(jarr.devices()))
        if dev.platform == "cpu":
            return cpu(dev.id)
        return Context("tpu", dev.id)
    except Exception:
        return current_context()


def _is_basic_index(key):
    basic = (int, slice, type(None), type(Ellipsis), _np.integer)
    if isinstance(key, basic):
        return True
    if isinstance(key, tuple):
        return all(isinstance(k, basic) for k in key)
    return False


def _check_bool_index(key):
    def bad(k):
        if isinstance(k, NDArray) and k.dtype == _np.bool_:
            return True
        if isinstance(k, _np.ndarray) and k.dtype == _np.bool_:
            return True
        return False
    items = key if isinstance(key, tuple) else (key,)
    for k in items:
        if bad(k):
            raise MXNetError("boolean-mask indexing produces dynamic shapes "
                             "and is not supported (reference NDArray raises "
                             "for unsupported index types); use nd.where or "
                             "contrib.boolean_mask alternatives")


def _convert_index(key):
    if isinstance(key, NDArray):
        return key._data.astype("int32")
    if isinstance(key, tuple):
        return tuple(_convert_index(k) for k in key)
    if isinstance(key, list):
        return _np.asarray(key)
    return key


def _binary(lhs, rhs, tensor_op, scalar_op):
    if isinstance(rhs, NDArray):
        if tensor_op is None:
            raise TypeError("unsupported operand")
        return _apply_op(tensor_op, [lhs, rhs], {})
    if isinstance(rhs, (int, float, bool, _np.generic)):
        return _apply_op(scalar_op, [lhs], {"scalar": float(rhs)})
    if isinstance(rhs, _np.ndarray):
        return _apply_op(tensor_op, [lhs, array(rhs, ctx=lhs.context)], {})
    import jax
    if isinstance(rhs, jax.Array) and tensor_op is not None:
        # raw jax array or tracer operand (fused optimizer traces inject
        # lr/wd/t as tracer scalars); broadcasting covers the scalar case
        return _apply_op(tensor_op, [lhs, NDArray(rhs, ctx=lhs.context)], {})
    return NotImplemented


# ---------------------------------------------------------------------------
# Eager dispatch — the Imperative::Invoke equivalent
# ---------------------------------------------------------------------------

def _apply_op(op_name, data, kwargs, out=None):
    return invoke(_reg.get(op_name), data, kwargs, out=out)


def invoke(op, data, kwargs, out=None):
    """Run a registered op on NDArray inputs eagerly.

    Mirrors `Imperative::Invoke` (`src/imperative/imperative.cc:87`): attrs are
    canonicalized, the XLA executable is fetched from the jit cache
    (`PushFCompute` analogue), outputs are wrapped, aux states written back,
    and the call recorded on the autograd tape when recording.
    """
    kwargs = dict(kwargs)
    kwargs.pop("name", None)
    kwargs.pop("attr", None)
    ctx_kw = kwargs.pop("ctx", None) if "ctx" not in op.params else None
    if "ctx" in op.params:
        ctx_kw = kwargs.get("ctx")
    params = op.canonicalize_params(kwargs)
    ctx_param = params.pop("ctx", None)
    ctx = ctx_kw or ctx_param

    if op.mode_dependent:
        params["_train"] = _autograd.is_training()

    # sparse inputs densify first (the documented TPU stance — reference
    # MKLDNN fallback does the same storage-type fallback); checked inline
    # to keep the common dense case free of extra passes
    for i, d in enumerate(data):
        if getattr(d, "_stype", "default") != "default":
            data = list(data)
            data[i] = d.tostype("default")
    # promote host-staged inputs to their claimed device first, so the op
    # result is committed to the right device and the output ctx is honest
    for d in data:
        if isinstance(d, NDArray) and isinstance(d._data, _np.ndarray):
            import jax
            _engine.unstage(d)
            d._data = jax.device_put(d._data, d._ctx.jax_device)

    in_arrays = [d._data if isinstance(d, NDArray) else d for d in data]
    n_aux = op.num_aux(params)

    if op.dynamic_params:
        import jax.numpy as jnp
        for pname in op.dynamic_params:
            pval = params.pop(pname)
            if isinstance(pval, NDArray):  # traced scalar (fused optimizer)
                pval = pval._data
            in_arrays.append(jnp.asarray(pval, dtype="float32"))

    if op.needs_rng:
        from .. import random as _random
        in_arrays = in_arrays + [_random.next_key()]

    from .. import profiler as _profiler
    try:
        if _profiler._imperative_active():
            # honest per-op timing requires waiting out async dispatch;
            # only paid while the profiler runs (profile_imperative)
            import time as _time
            import jax as _jax
            t0 = _time.perf_counter()
            results = _reg.eager_call(op, params, in_arrays)
            _jax.block_until_ready(results)
            _profiler.record_op(op.name,
                                (_time.perf_counter() - t0) * 1e6)
        else:
            results = _reg.eager_call(op, params, in_arrays)
    except Exception as e:
        # an input whose buffer a fused step's donation consumed dies
        # inside jax with an opaque "Array has been deleted" — name the
        # parameter instead (analysis.donation)
        for d in data:
            if isinstance(d, NDArray):
                _raise_use_after_donation(d._data, e)
        raise
    n_out = op.num_outputs(params)
    vis, aux_updates = results[:n_out], results[n_out:]

    # device/context resolution
    if data:
        out_ctx = data[0].context if isinstance(data[0], NDArray) else current_context()
    else:
        out_ctx = ctx if isinstance(ctx, Context) else (
            Context(*_parse_ctx(ctx)) if isinstance(ctx, str) else current_context())
        import jax
        vis = tuple(jax.device_put(v, out_ctx.jax_device) for v in vis)

    for v in vis:
        _engine.track(v, op=op.name)

    # write updated aux states in place (BatchNorm running stats etc.)
    if aux_updates and n_aux:
        aux_arrays = data[-n_aux:]
        for a, upd in zip(aux_arrays, aux_updates):
            if isinstance(a, NDArray):
                a._data = upd  # bypass recording guard: aux carries no grad

    outputs = [NDArray(v, ctx=out_ctx) for v in vis]

    if (_autograd.is_recording() and not op.stop_grad
            and any(getattr(d, "_requires_grad", False) for d in data
                    if isinstance(d, NDArray))):
        nd_inputs = [d if isinstance(d, NDArray) else None for d in data]
        _autograd._record_op(op, params, nd_inputs, in_arrays, outputs, n_out)
        for o in outputs:
            o._requires_grad = True

    if out is not None:
        outs = out if isinstance(out, (list, tuple)) else [out]
        if len(outs) != len(outputs):
            raise MXNetError(f"Operator {op.name}: out= expects {len(outputs)} "
                             f"arrays, got {len(outs)}")
        if _autograd.is_recording() and any(
                getattr(d, "_requires_grad", False) for d in data
                if isinstance(d, NDArray)):
            # reference raises for in-place outputs while recording
            raise MXNetError("Assigning to out= arrays is not supported when "
                             "recording with autograd")
        for tgt, o in zip(outs, outputs):
            tgt._set_data(o._data.astype(tgt.dtype))
        return out
    if len(outputs) == 1:
        return outputs[0]
    return outputs


def imperative_invoke(op_name, *data, **kwargs):
    """String-name invoke (the `MXImperativeInvokeEx` surface)."""
    out = kwargs.pop("out", None)
    return invoke(_reg.get(op_name), list(data), kwargs, out=out)


def _parse_ctx(s):
    dt, _, rest = s.partition("(")
    did = int(rest.rstrip(")")) if rest else 0
    return dt, did


# ---------------------------------------------------------------------------
# Creation functions (reference python/mxnet/ndarray/ndarray.py + utils)
# ---------------------------------------------------------------------------

def array(source_array, ctx=None, dtype=None):
    import jax
    import jax.numpy as jnp
    ctx = ctx or current_context()
    if isinstance(source_array, NDArray):
        src = source_array._data
        if dtype is not None:
            src = src.astype(np_dtype(dtype))
        return NDArray(jax.device_put(src, ctx.jax_device), ctx=ctx)
    # MXNet semantics: dtype defaults to float32 for any non-NDArray source
    # (reference `python/mxnet/ndarray/ndarray.py array()`)
    np_arr = _np.asarray(source_array,
                         dtype=np_dtype(dtype) if dtype is not None else _np.float32)
    # put the host buffer straight onto the target device: routing through
    # jnp.asarray first would land it on the DEFAULT device (the TPU) and
    # then copy back — a full round trip over the chip link for cpu arrays.
    # CPU targets: device_put ZERO-COPIES matching-dtype numpy buffers, but
    # mx.nd.array promises copy semantics (the caller may mutate or recycle
    # its buffer) — take a private copy when jax would alias
    if ctx.jax_device.platform == "cpu" and np_arr is source_array:
        np_arr = np_arr.copy()
    return NDArray(jax.device_put(np_arr, ctx.jax_device), ctx=ctx)


def _staged(np_arr, ctx):
    """Host-staged NDArray under engine bulk mode: the buffer lives in host
    memory until the engine flush batches all pending transfers
    (reference bulk-execution fusion, `include/mxnet/engine.h:308-313`)."""
    out = NDArray(np_arr, ctx=ctx or current_context())
    _engine.stage(out)
    return out


def empty(shape, ctx=None, dtype=None):
    return zeros(shape, ctx=ctx, dtype=dtype)


def zeros(shape, ctx=None, dtype=None, **kwargs):
    if isinstance(shape, int):
        shape = (shape,)
    if _engine.bulk_active():
        return _staged(_np.zeros(shape, np_dtype(dtype or "float32")), ctx)
    return _apply_op("_zeros", [], {"shape": shape, "dtype": dtype_name(dtype or "float32"),
                                    "ctx": ctx or current_context()})


def ones(shape, ctx=None, dtype=None, **kwargs):
    if isinstance(shape, int):
        shape = (shape,)
    if _engine.bulk_active():
        return _staged(_np.ones(shape, np_dtype(dtype or "float32")), ctx)
    return _apply_op("_ones", [], {"shape": shape, "dtype": dtype_name(dtype or "float32"),
                                   "ctx": ctx or current_context()})


def full(shape, val, ctx=None, dtype=None, out=None):
    if isinstance(shape, int):
        shape = (shape,)
    if _engine.bulk_active() and out is None:
        return _staged(_np.full(shape, val, np_dtype(dtype or "float32")), ctx)
    return _apply_op("_full", [], {"shape": shape, "value": val,
                                   "dtype": dtype_name(dtype or "float32"),
                                   "ctx": ctx or current_context()}, out=out)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None):
    return _apply_op("_arange", [], {"start": start, "stop": stop, "step": step,
                                     "repeat": repeat,
                                     "dtype": dtype_name(dtype or "float32"),
                                     "ctx": ctx or current_context()})


def eye(N, M=0, k=0, ctx=None, dtype=None):
    return _apply_op("_eye", [], {"N": N, "M": M, "k": k,
                                  "dtype": dtype_name(dtype or "float32"),
                                  "ctx": ctx or current_context()})


def linspace(start, stop, num, endpoint=True, ctx=None, dtype=None):
    return _apply_op("_linspace", [], {"start": start, "stop": stop, "num": num,
                                       "endpoint": endpoint,
                                       "dtype": dtype_name(dtype or "float32"),
                                       "ctx": ctx or current_context()})


def concatenate(arrays, axis=0, always_copy=True):
    return _apply_op("Concat", list(arrays),
                     {"num_args": len(arrays), "dim": axis})


def moveaxis(tensor, source, destination):
    axes = list(range(tensor.ndim))
    axes.remove(source % tensor.ndim)
    axes.insert(destination % tensor.ndim, source % tensor.ndim)
    return _apply_op("transpose", [tensor], {"axes": tuple(axes)})


def waitall():
    _engine.waitall()


# -- binary helpers accepting NDArray|scalar on either side (reference
# `python/mxnet/ndarray/ndarray.py` maximum/minimum/add/... wrappers)

def _scalar_or_tensor(lhs, rhs, tensor_op, lscalar_op, rscalar_op):
    if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
        return _apply_op(tensor_op, [lhs, rhs], {})
    if isinstance(lhs, NDArray):
        return _apply_op(lscalar_op, [lhs], {"scalar": float(rhs)})
    if isinstance(rhs, NDArray):
        return _apply_op(rscalar_op, [rhs], {"scalar": float(lhs)})
    raise TypeError("at least one argument must be NDArray")


def maximum(lhs, rhs):
    return _scalar_or_tensor(lhs, rhs, "broadcast_maximum",
                             "_maximum_scalar", "_maximum_scalar")


def minimum(lhs, rhs):
    return _scalar_or_tensor(lhs, rhs, "broadcast_minimum",
                             "_minimum_scalar", "_minimum_scalar")


def add(lhs, rhs):
    return _scalar_or_tensor(lhs, rhs, "broadcast_add",
                             "_plus_scalar", "_plus_scalar")


def subtract(lhs, rhs):
    return _scalar_or_tensor(lhs, rhs, "broadcast_sub",
                             "_minus_scalar", "_rminus_scalar")


def multiply(lhs, rhs):
    return _scalar_or_tensor(lhs, rhs, "broadcast_mul",
                             "_mul_scalar", "_mul_scalar")


def divide(lhs, rhs):
    return _scalar_or_tensor(lhs, rhs, "broadcast_div",
                             "_div_scalar", "_rdiv_scalar")


def modulo(lhs, rhs):
    return _scalar_or_tensor(lhs, rhs, "broadcast_mod",
                             "_mod_scalar", "_rmod_scalar")


def power(lhs, rhs):
    return _scalar_or_tensor(lhs, rhs, "broadcast_power",
                             "_power_scalar", "_rpower_scalar")


# ---------------------------------------------------------------------------
# Attach registry-op convenience methods to NDArray (the reference code-gens
# these from the op registry at import, `python/mxnet/ndarray/register.py`).
# ---------------------------------------------------------------------------

_METHOD_OPS = [
    "sum", "mean", "prod", "max", "min", "argmax", "argmin", "norm",
    "abs", "sign", "exp", "log", "log2", "log10", "log1p", "expm1",
    "sqrt", "rsqrt", "square", "cbrt", "sin", "cos", "tan", "arcsin",
    "arccos", "arctan", "sinh", "cosh", "tanh", "arcsinh", "arccosh",
    "arctanh", "sigmoid", "relu", "softmax", "log_softmax", "clip",
    "round", "rint", "floor", "ceil", "trunc", "fix", "flatten",
    "expand_dims", "squeeze", "swapaxes", "split", "slice", "slice_axis",
    "take", "one_hot", "topk", "sort", "argsort", "tile", "repeat",
    "pad", "flip", "transpose", "dot", "batch_dot", "broadcast_to",
    "broadcast_like", "broadcast_axes", "zeros_like", "ones_like",
    "reshape_like", "diag", "nansum", "nanprod", "reciprocal", "erf",
    "erfinv", "gamma", "gammaln", "degrees", "radians", "softsign",
    "argmax_channel", "shape_array", "size_array",
]


def _make_method(op_name):
    def method(self, *args, **kwargs):
        out = kwargs.pop("out", None)
        return invoke(_reg.get(op_name), [self] + list(args), kwargs, out=out)
    method.__name__ = op_name
    return method


def _attach_methods():
    for name in _METHOD_OPS:
        if _reg.maybe_get(name) is None:
            continue
        if hasattr(NDArray, name):
            continue
        setattr(NDArray, name, _make_method(name))


_attach_methods()
