"""`mx.nd.image` ops (reference `src/operator/image/image_random.cc`):
to_tensor, normalize, flips — the Gluon vision-transform backend."""
from __future__ import annotations

import jax.numpy as jnp

from .ndarray import NDArray


def to_tensor(data):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (reference image_random.cc ToTensor)."""
    x = data._data.astype("float32") / 255.0
    if x.ndim == 3:
        x = jnp.transpose(x, (2, 0, 1))
    elif x.ndim == 4:
        x = jnp.transpose(x, (0, 3, 1, 2))
    return NDArray(x, ctx=data.context)


def normalize(data, mean, std):
    x = data._data
    mean = jnp.asarray(mean, x.dtype)
    std = jnp.asarray(std, x.dtype)
    nd = x.ndim
    shape = (-1,) + (1,) * (2 if nd >= 3 else 0)
    return NDArray((x - mean.reshape(shape)) / std.reshape(shape),
                   ctx=data.context)


def flip_left_right(data):
    return NDArray(jnp.flip(data._data, axis=-1), ctx=data.context)


def flip_top_bottom(data):
    return NDArray(jnp.flip(data._data, axis=-2), ctx=data.context)


def random_flip_left_right(data):
    from .. import random as _r
    import jax
    if jax.random.bernoulli(_r.next_key()):
        return flip_left_right(data)
    return data


def random_flip_top_bottom(data):
    from .. import random as _r
    import jax
    if jax.random.bernoulli(_r.next_key()):
        return flip_top_bottom(data)
    return data
