"""`mx.nd` — the eager NDArray package (reference `python/mxnet/ndarray/`)."""
from . import ndarray
from .ndarray import (NDArray, array, zeros, ones, full, empty, arange, eye,
                      linspace, concatenate, moveaxis, waitall,
                      imperative_invoke, invoke, maximum, minimum, add,
                      subtract, multiply, divide, modulo, power)
from . import register as _register
import sys as _sys

# generated op functions (nd.sum, nd.FullyConnected, ...)
_register.populate(_sys.modules[__name__])

from . import random  # noqa: E402,F401
from . import utils   # noqa: E402
from .utils import save, load  # noqa: E402,F401
from . import sparse  # noqa: E402,F401
from . import linalg  # noqa: E402,F401
from . import contrib  # noqa: E402,F401
from . import image as _image_mod  # noqa: E402,F401
