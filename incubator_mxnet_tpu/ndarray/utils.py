"""NDArray save/load (reference `python/mxnet/ndarray/utils.py:149-222`,
binary container `src/ndarray/ndarray.cc:1537`).

Format: the reference's container is a dmlc binary stream with a magic word,
an NDArray list and a name list.  We write the same *logical* content —
(names, arrays) — as an uncompressed ``.npz``-style zip with a magic entry, so
checkpoints are portable and inspectable.  `load` also accepts real numpy
``.npz`` files.  Byte-compatibility with reference `.params` files is provided
by `incubator_mxnet_tpu.compat.mxnet_params` (reader).
"""
from __future__ import annotations

import io
import zipfile

import numpy as np

from .ndarray import NDArray, array
from ..base import MXNetError

_MAGIC = "__incubator_mxnet_tpu_v1__"


def save(fname, data):
    """Save NDArrays (reference `mx.nd.save`): list or dict of arrays."""
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
    elif isinstance(data, (list, tuple)):
        names = [str(i) for i in range(len(data))]
        arrays = list(data)
    else:
        raise MXNetError("save: data must be NDArray, list, or dict")
    npys = {}
    for n, a in zip(names, arrays):
        npys[n] = a.asnumpy() if isinstance(a, NDArray) else np.asarray(a)
    with zipfile.ZipFile(fname, "w", zipfile.ZIP_STORED) as zf:
        zf.writestr(_MAGIC, b"1")
        meta_is_list = isinstance(data, (list, tuple))
        zf.writestr("__meta__", b"list" if meta_is_list else b"dict")
        for n, arr in npys.items():
            buf = io.BytesIO()
            np.save(buf, arr, allow_pickle=False)
            zf.writestr(n + ".npy", buf.getvalue())


def load(fname, ctx=None):
    """Load NDArrays saved by `save` (reference `mx.nd.load`)."""
    with zipfile.ZipFile(fname, "r") as zf:
        names = zf.namelist()
        if _MAGIC not in names:
            # plain npz fallback
            out = {}
            for n in names:
                if n.endswith(".npy"):
                    out[n[:-4]] = array(np.load(io.BytesIO(zf.read(n))), ctx=ctx)
            return out
        meta = zf.read("__meta__").decode()
        out = {}
        for n in names:
            if n.endswith(".npy"):
                out[n[:-4]] = array(np.load(io.BytesIO(zf.read(n))), ctx=ctx)
        if meta == "list":
            return [out[str(i)] for i in range(len(out))]
        return out
