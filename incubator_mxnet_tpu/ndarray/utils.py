"""NDArray save/load (reference `python/mxnet/ndarray/utils.py:149-222`,
binary container `src/ndarray/ndarray.cc:1537`).

`save` writes the reference's dmlc binary container byte-for-byte
(`incubator_mxnet_tpu.compat.mxnet_params`), so checkpoints interchange
with reference MXNet in both directions.  `load` reads that container plus
two legacy fallbacks: this framework's earlier zip format and plain numpy
``.npz`` files.
"""
from __future__ import annotations

import io
import struct
import zipfile

import numpy as np

from .ndarray import NDArray, array
from ..base import MXNetError

_MAGIC = "__incubator_mxnet_tpu_v1__"


def save(fname, data):
    """Save NDArrays (reference `mx.nd.save`): list or dict of arrays.

    Lists are saved unnamed (loading yields a list), dicts named — the
    reference's exact semantics.
    """
    from ..compat.mxnet_params import save_params
    if isinstance(data, NDArray):
        data = [data]
    if not isinstance(data, (dict, list, tuple)):
        raise MXNetError("save: data must be NDArray, list, or dict")
    save_params(fname, data)


def load(fname, ctx=None):
    """Load NDArrays saved by `save` or by reference MXNet (`mx.nd.load`)."""
    with open(fname, "rb") as f:
        head = f.read(8)
    if len(head) == 8 and struct.unpack("<Q", head)[0] == 0x112:
        from ..compat.mxnet_params import load_params
        out = load_params(fname)
        if ctx is not None:
            if isinstance(out, dict):
                out = {k: v.as_in_context(ctx) for k, v in out.items()}
            else:
                out = [v.as_in_context(ctx) for v in out]
        return out
    return _load_zip(fname, ctx)


def _load_zip(fname, ctx=None):
    """Legacy formats: this framework's v1 zip container and numpy .npz."""
    with zipfile.ZipFile(fname, "r") as zf:
        names = zf.namelist()
        if _MAGIC not in names:
            out = {}
            for n in names:
                if n.endswith(".npy"):
                    out[n[:-4]] = array(np.load(io.BytesIO(zf.read(n))),
                                        ctx=ctx)
            return out
        meta = zf.read("__meta__").decode()
        out = {}
        for n in names:
            if n.endswith(".npy"):
                out[n[:-4]] = array(np.load(io.BytesIO(zf.read(n))), ctx=ctx)
        if meta == "list":
            return [out[str(i)] for i in range(len(out))]
        return out
