"""`mx.nd.linalg` namespace (reference `python/mxnet/ndarray/linalg.py`)."""
from __future__ import annotations

from .ndarray import invoke
from ..ops import registry as _reg


def _wrap(opname):
    def fn(*args, **kwargs):
        out = kwargs.pop("out", None)
        return invoke(_reg.get(opname), list(args), kwargs, out=out)
    fn.__name__ = opname.replace("linalg_", "")
    return fn


gemm = _wrap("linalg_gemm")
gemm2 = _wrap("linalg_gemm2")
potrf = _wrap("linalg_potrf")
potri = _wrap("linalg_potri")
trsm = _wrap("linalg_trsm")
trmm = _wrap("linalg_trmm")
syrk = _wrap("linalg_syrk")
gelqf = _wrap("linalg_gelqf")
syevd = _wrap("linalg_syevd")
sumlogdiag = _wrap("linalg_sumlogdiag")
extractdiag = _wrap("linalg_extractdiag")
makediag = _wrap("linalg_makediag")
inverse = _wrap("linalg_inverse")
det = _wrap("linalg_det")
slogdet = _wrap("linalg_slogdet")
