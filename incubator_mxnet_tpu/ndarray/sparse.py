"""Sparse NDArray types (reference `python/mxnet/ndarray/sparse.py`,
`include/mxnet/ndarray.h:61-65` row_sparse/csr storage types).

TPU design stance (SURVEY.md §7 hard part (d)): TPUs have no efficient
scatter/gather sparse formats, so sparse storage lives host-side as
numpy-backed structures; `tostype('default')` densifies onto the device and
dense↔sparse conversions are explicit.  The API surface (RowSparseNDArray /
CSRNDArray / cast_storage / sparse dot) is preserved for parity; compute on
sparse inputs densifies first (documented, as MKLDNN fallback does in the
reference).
"""
from __future__ import annotations

import numpy as np

from .ndarray import NDArray, array as _dense_array
from ..base import MXNetError
from ..context import current_context


class BaseSparseNDArray(NDArray):
    pass


def aggregate_row_sparse(indices, values):
    """Sum duplicate row ids into one (sorted-unique ids, summed values)
    pair.

    A minibatch touching the same embedding row twice produces duplicate
    ids; the lazy optimizer paths gather/scatter per id, so duplicates
    must be pre-summed or momentum/Adam state rows are scattered
    last-write-wins.  The embedding push path and `_row_sparse_grad`
    both normalize through here."""
    indices = np.asarray(indices, dtype=np.int64)
    values = np.asarray(values)
    if len(indices) <= 1:
        return indices, values
    uniq, inv = np.unique(indices, return_inverse=True)
    if len(uniq) == len(indices) and np.array_equal(uniq, indices):
        return indices, values   # already sorted-unique: no copy
    out = np.zeros((len(uniq),) + values.shape[1:], dtype=values.dtype)
    np.add.at(out, inv, values)
    return uniq, out


class RowSparseNDArray(BaseSparseNDArray):
    """row_sparse: (indices, values) over axis 0 (reference sparse.py:RowSparseNDArray)."""

    def __init__(self, data, indices, shape, ctx=None):
        self._np_data = np.asarray(data)
        self._np_indices = np.asarray(indices, dtype=np.int64)
        self._shape = tuple(shape)
        self._ctx = ctx or current_context()
        self._grad = None
        self._grad_req = None
        self._requires_grad = False
        self._stype = "row_sparse"
        self._data = None  # dense buffer created lazily by tostype

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self._np_data.dtype

    @property
    def indices(self):
        return _dense_array(self._np_indices, ctx=self._ctx)

    @property
    def data(self):
        return _dense_array(self._np_data, ctx=self._ctx)

    def asnumpy(self):
        out = np.zeros(self._shape, dtype=self._np_data.dtype)
        if len(self._np_indices):
            out[self._np_indices] = self._np_data
        return out

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype == "default":
            return _dense_array(self.asnumpy(), ctx=self._ctx)
        raise MXNetError(f"cannot cast row_sparse to {stype}")

    def wait_to_read(self):
        pass

    def __repr__(self):
        return f"<RowSparseNDArray {self._shape} @{self._ctx}>"


class CSRNDArray(BaseSparseNDArray):
    """csr: (data, indices, indptr) 2-D sparse (reference sparse.py:CSRNDArray)."""

    def __init__(self, data, indices, indptr, shape, ctx=None):
        self._np_data = np.asarray(data)
        self._np_indices = np.asarray(indices, dtype=np.int64)
        self._np_indptr = np.asarray(indptr, dtype=np.int64)
        self._shape = tuple(shape)
        self._ctx = ctx or current_context()
        self._grad = None
        self._grad_req = None
        self._requires_grad = False
        self._stype = "csr"
        self._data = None

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self._np_data.dtype

    @property
    def indices(self):
        return _dense_array(self._np_indices, ctx=self._ctx)

    @property
    def indptr(self):
        return _dense_array(self._np_indptr, ctx=self._ctx)

    @property
    def data(self):
        return _dense_array(self._np_data, ctx=self._ctx)

    def asnumpy(self):
        m, n = self._shape
        out = np.zeros((m, n), dtype=self._np_data.dtype)
        for i in range(m):
            for jpos in range(self._np_indptr[i], self._np_indptr[i + 1]):
                out[i, self._np_indices[jpos]] = self._np_data[jpos]
        return out

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype == "default":
            return _dense_array(self.asnumpy(), ctx=self._ctx)
        raise MXNetError(f"cannot cast csr to {stype}")

    def wait_to_read(self):
        pass

    def __repr__(self):
        return f"<CSRNDArray {self._shape} @{self._ctx}>"


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        if isinstance(data, NDArray):
            data = data.asnumpy()
        if isinstance(indices, NDArray):
            indices = indices.asnumpy()
        return RowSparseNDArray(np.asarray(data, dtype=dtype), indices, shape, ctx)
    dense = np.asarray(arg1.asnumpy() if isinstance(arg1, NDArray) else arg1,
                       dtype=dtype)
    nz = np.where(np.any(dense.reshape(dense.shape[0], -1) != 0, axis=1))[0]
    return RowSparseNDArray(dense[nz], nz, dense.shape, ctx)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        vals = [x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)
                for x in (data, indices, indptr)]
        return CSRNDArray(vals[0].astype(dtype) if dtype else vals[0],
                          vals[1], vals[2], shape, ctx)
    dense = np.asarray(arg1.asnumpy() if isinstance(arg1, NDArray) else arg1,
                       dtype=dtype)
    m, n = dense.shape
    indptr = [0]
    indices = []
    data = []
    for i in range(m):
        nz = np.where(dense[i] != 0)[0]
        indices.extend(nz.tolist())
        data.extend(dense[i, nz].tolist())
        indptr.append(len(indices))
    return CSRNDArray(np.asarray(data, dtype=dense.dtype), indices, indptr,
                      (m, n), ctx)


def cast_storage(arr, stype):
    """Reference `cast_storage.cc`."""
    if stype == "default":
        return arr.tostype("default") if isinstance(arr, BaseSparseNDArray) else arr
    if stype == "row_sparse":
        return row_sparse_array(arr.asnumpy())
    if stype == "csr":
        return csr_matrix(arr.asnumpy())
    raise MXNetError(f"unknown stype {stype}")


def zeros(stype, shape, ctx=None, dtype=None):
    dtype = dtype or "float32"
    if stype == "row_sparse":
        return RowSparseNDArray(np.zeros((0,) + tuple(shape[1:]), dtype=dtype),
                                np.zeros((0,), dtype=np.int64), shape, ctx)
    if stype == "csr":
        return CSRNDArray(np.zeros((0,), dtype=dtype), [], [0] * (shape[0] + 1),
                          shape, ctx)
    from . import ndarray as _nd
    return _nd.zeros(shape, ctx=ctx, dtype=dtype)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse dot densifies (documented TPU fallback)."""
    from .ndarray import _apply_op
    l = lhs.tostype("default") if isinstance(lhs, BaseSparseNDArray) else lhs
    r = rhs.tostype("default") if isinstance(rhs, BaseSparseNDArray) else rhs
    return _apply_op("dot", [l, r], {"transpose_a": transpose_a,
                                     "transpose_b": transpose_b})
