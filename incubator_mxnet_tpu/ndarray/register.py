"""Generate module-level NDArray op functions from the registry.

Mirrors the reference's import-time code generation
(`python/mxnet/ndarray/register.py:30-169` `_make_ndarray_function` over
`MXSymbolListAtomicSymbolCreators`): every registered op becomes a function in
`incubator_mxnet_tpu.ndarray` (public names) / `.ndarray._internal`
(underscore names), with the op docstring attached.
"""
from __future__ import annotations

import sys
import types

from ..ops import registry as _reg
from .ndarray import NDArray, invoke

_internal = types.ModuleType("incubator_mxnet_tpu.ndarray._internal")
sys.modules["incubator_mxnet_tpu.ndarray._internal"] = _internal


def _make_function(op):
    def fn(*args, **kwargs):
        out = kwargs.pop("out", None)
        data = []
        for a in args:
            if isinstance(a, NDArray):
                data.append(a)
            elif isinstance(a, (list, tuple)) and all(
                    isinstance(x, NDArray) for x in a):
                data.extend(a)
            else:
                raise TypeError(
                    f"Operator {op.name}: positional arguments must be "
                    f"NDArray, got {type(a).__name__}")
        if op.variadic_param and op.variadic_param not in kwargs:
            kwargs[op.variadic_param] = len(data)
        return invoke(op, data, kwargs, out=out)

    fn.__name__ = op.name
    fn.__doc__ = op.doc or f"TPU-native operator `{op.name}`."
    return fn


def populate(target_module):
    """Attach one function per registered op (call after all op modules load)."""
    seen = set()
    for name in _reg.list_ops():
        op = _reg.get(name)
        if id(op) in seen and name != op.name:
            pass
        seen.add(id(op))
        f = _make_function(op)
        f.__name__ = name
        setattr(_internal, name, f)
        if not name.startswith("_"):
            if not hasattr(target_module, name):
                setattr(target_module, name, f)
    target_module._internal = _internal
