"""`mx.nd.random` (reference `python/mxnet/ndarray/random.py`)."""
from __future__ import annotations

from .ndarray import NDArray, invoke
from ..ops import registry as _reg


def _rand(opname, sample_opname, *dist_args, shape=(), dtype="float32",
          ctx=None, out=None, **kwargs):
    if dist_args and isinstance(dist_args[0], NDArray):
        op = _reg.get(sample_opname)
        return invoke(op, list(dist_args), {"shape": shape, "dtype": dtype},
                      out=out)
    op = _reg.get(opname)
    params = dict(kwargs)
    params.update({"shape": shape, "dtype": dtype, "ctx": ctx})
    return invoke(op, [], params, out=out)


def uniform(low=0, high=1, shape=(), dtype="float32", ctx=None, out=None):
    return _rand("_random_uniform", "_sample_uniform", *(
        (low, high) if isinstance(low, NDArray) else ()),
        shape=shape, dtype=dtype, ctx=ctx, out=out,
        **({} if isinstance(low, NDArray) else {"low": low, "high": high}))


def normal(loc=0, scale=1, shape=(), dtype="float32", ctx=None, out=None):
    return _rand("_random_normal", "_sample_normal", *(
        (loc, scale) if isinstance(loc, NDArray) else ()),
        shape=shape, dtype=dtype, ctx=ctx, out=out,
        **({} if isinstance(loc, NDArray) else {"loc": loc, "scale": scale}))


def randn(*shape, loc=0.0, scale=1.0, dtype="float32", ctx=None):
    return normal(loc=loc, scale=scale, shape=shape, dtype=dtype, ctx=ctx)


def gamma(alpha=1, beta=1, shape=(), dtype="float32", ctx=None, out=None):
    return _rand("_random_gamma", "_sample_gamma", *(
        (alpha, beta) if isinstance(alpha, NDArray) else ()),
        shape=shape, dtype=dtype, ctx=ctx, out=out,
        **({} if isinstance(alpha, NDArray) else {"alpha": alpha, "beta": beta}))


def exponential(lam=1, shape=(), dtype="float32", ctx=None, out=None):
    return _rand("_random_exponential", "_random_exponential",
                 shape=shape, dtype=dtype, ctx=ctx, out=out, lam=lam)


def poisson(lam=1, shape=(), dtype="float32", ctx=None, out=None):
    return _rand("_random_poisson", "_random_poisson",
                 shape=shape, dtype=dtype, ctx=ctx, out=out, lam=lam)


def negative_binomial(k=1, p=1, shape=(), dtype="float32", ctx=None, out=None):
    return _rand("_random_negative_binomial", "_random_negative_binomial",
                 shape=shape, dtype=dtype, ctx=ctx, out=out, k=k, p=p)


def generalized_negative_binomial(mu=1, alpha=1, shape=(), dtype="float32",
                                  ctx=None, out=None):
    return _rand("_random_generalized_negative_binomial",
                 "_random_generalized_negative_binomial",
                 shape=shape, dtype=dtype, ctx=ctx, out=out, mu=mu, alpha=alpha)


def randint(low, high, shape=(), dtype="int32", ctx=None, out=None):
    return _rand("_random_randint", "_random_randint",
                 shape=shape, dtype=dtype, ctx=ctx, out=out, low=low, high=high)


def multinomial(data, shape=(), get_prob=False, out=None, dtype="int32"):
    op = _reg.get("_sample_multinomial")
    return invoke(op, [data], {"shape": shape, "get_prob": get_prob,
                               "dtype": dtype}, out=out)


def shuffle(data, out=None):
    return invoke(_reg.get("_shuffle"), [data], {}, out=out)


def seed(seed_state, ctx="all"):
    from .. import random as _r
    _r.seed(seed_state, ctx)
