"""Detection augmentation pipeline (reference
`python/mxnet/image/detection.py`): augmenters transform (image, boxes)
PAIRS — crops/flips/pads must move the ground-truth boxes with the
pixels.  Boxes are normalized [cls, x1, y1, x2, y2] rows, -1-padded.
"""
from __future__ import annotations

import json
import random as _pyrandom

import numpy as np

from .base import MXNetError
from .image import Augmenter, imdecode
from .io import DataIter, DataBatch, DataDesc
from .ndarray.ndarray import NDArray, array

__all__ = ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "CreateDetAugmenter", "ImageDetIter"]


class DetAugmenter:
    """Base detection augmenter (reference `detection.py:39`)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs],
                          default=lambda o: o.tolist()
                          if hasattr(o, "tolist") else str(o))

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Lift an image-only augmenter; labels pass through
    (reference `detection.py:65`)."""

    def __init__(self, augmenter):
        if not isinstance(augmenter, Augmenter):
            raise MXNetError("DetBorrowAug expects an image Augmenter")
        super().__init__(augmenter=augmenter.dumps())
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly pick one of several augmenters (reference `:90`)."""

    def __init__(self, aug_list, skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = aug_list
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if _pyrandom.random() < self.skip_prob or not self.aug_list:
            return src, label
        return _pyrandom.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror image AND boxes (reference `:126`)."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if _pyrandom.random() < self.p:
            img = src.asnumpy() if isinstance(src, NDArray) else src
            src = array(img[:, ::-1].copy(), dtype="uint8")
            label = label.copy()
            valid = label[:, 0] >= 0
            x1 = label[valid, 1].copy()
            label[valid, 1] = 1.0 - label[valid, 3]
            label[valid, 3] = 1.0 - x1
        return src, label


class DetRandomCropAug(DetAugmenter):
    """IOU-constrained random crop (reference `:152`): sample a crop whose
    IOU with some ground-truth box exceeds `min_object_covered`; boxes are
    clipped/dropped relative to the crop."""

    def __init__(self, min_object_covered=0.1, aspect_ratio_range=(0.75,
                 1.33), area_range=(0.05, 1.0), max_attempts=50):
        super().__init__(min_object_covered=min_object_covered,
                         aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts

    def _sample_crop(self, label):
        for _ in range(self.max_attempts):
            area = _pyrandom.uniform(*self.area_range)
            ratio = _pyrandom.uniform(*self.aspect_ratio_range)
            w = min(1.0, np.sqrt(area * ratio))
            h = min(1.0, np.sqrt(area / ratio))
            x0 = _pyrandom.uniform(0, 1 - w)
            y0 = _pyrandom.uniform(0, 1 - h)
            crop = (x0, y0, x0 + w, y0 + h)
            valid = label[:, 0] >= 0
            if not valid.any():
                return crop
            boxes = label[valid, 1:5]
            ix1 = np.maximum(boxes[:, 0], crop[0])
            iy1 = np.maximum(boxes[:, 1], crop[1])
            ix2 = np.minimum(boxes[:, 2], crop[2])
            iy2 = np.minimum(boxes[:, 3], crop[3])
            inter = np.maximum(ix2 - ix1, 0) * np.maximum(iy2 - iy1, 0)
            barea = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
            cover = inter / np.maximum(barea, 1e-12)
            if (cover >= self.min_object_covered).any():
                return crop
        return None

    def __call__(self, src, label):
        crop = self._sample_crop(label)
        if crop is None:
            return src, label
        img = src.asnumpy() if isinstance(src, NDArray) else src
        H, W = img.shape[:2]
        x0, y0, x1, y1 = crop
        px0, py0 = int(x0 * W), int(y0 * H)
        px1, py1 = max(px0 + 1, int(x1 * W)), max(py0 + 1, int(y1 * H))
        out = img[py0:py1, px0:px1]
        cw, ch = x1 - x0, y1 - y0
        new = np.full_like(label, -1.0)
        j = 0
        for row in label:
            if row[0] < 0:
                continue
            bx1 = (max(row[1], x0) - x0) / cw
            by1 = (max(row[2], y0) - y0) / ch
            bx2 = (min(row[3], x1) - x0) / cw
            by2 = (min(row[4], y1) - y0) / ch
            if bx2 - bx1 <= 0.001 or by2 - by1 <= 0.001:
                continue                  # box left the crop
            new[j, 0] = row[0]
            new[j, 1:5] = (bx1, by1, bx2, by2)
            new[j, 5:] = row[5:]
            j += 1
        return array(out, dtype="uint8"), new


class DetRandomPadAug(DetAugmenter):
    """Pad to a larger random canvas; boxes shrink into it
    (reference `:323`)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=50,
                 pad_val=(127, 127, 127)):
        super().__init__(aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts,
                         pad_val=pad_val)
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.pad_val = np.asarray(pad_val, np.uint8)

    def __call__(self, src, label):
        img = src.asnumpy() if isinstance(src, NDArray) else src
        H, W = img.shape[:2]
        scale = _pyrandom.uniform(*self.area_range)
        if scale <= 1.0:
            return src, label
        ratio = _pyrandom.uniform(*self.aspect_ratio_range)
        nw = int(W * np.sqrt(scale * ratio))
        nh = int(H * np.sqrt(scale / ratio))
        nw, nh = max(nw, W), max(nh, H)
        ox = _pyrandom.randint(0, nw - W)
        oy = _pyrandom.randint(0, nh - H)
        canvas = np.empty((nh, nw, img.shape[2]), img.dtype)
        canvas[:] = self.pad_val
        canvas[oy:oy + H, ox:ox + W] = img
        label = label.copy()
        valid = label[:, 0] >= 0
        label[valid, 1] = (label[valid, 1] * W + ox) / nw
        label[valid, 3] = (label[valid, 3] * W + ox) / nw
        label[valid, 2] = (label[valid, 2] * H + oy) / nh
        label[valid, 4] = (label[valid, 4] * H + oy) / nh
        return array(canvas, dtype="uint8"), label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, pca_noise=0,
                       hue=0, inter_method=2, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), max_attempts=50,
                       pad_val=(127, 127, 127)):
    """Reference `detection.py:482 CreateDetAugmenter`."""
    auglist = []
    if resize > 0:
        from .image import ResizeAug
        auglist.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                (area_range[0], min(1.0, area_range[1])),
                                max_attempts)
        auglist.append(DetRandomSelectAug([crop], 1 - rand_crop))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (1.0, max(1.0, area_range[1])), max_attempts,
                              pad_val)
        auglist.append(DetRandomSelectAug([pad], 1 - rand_pad))
    from .image import ForceResizeAug, CastAug, ColorNormalizeAug
    auglist.append(DetBorrowAug(ForceResizeAug((data_shape[2],
                                                data_shape[1]),
                                               inter_method)))
    auglist.append(DetBorrowAug(CastAug()))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(DataIter):
    """Detection iterator over .rec/list sources (reference
    `detection.py:594 ImageDetIter`): labels are (batch, max_objects, 5)."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root=None, imglist=None,
                 shuffle=False, aug_list=None, data_name="data",
                 label_name="label", max_objects=16, **kwargs):
        super().__init__(batch_size)
        from .image import ImageIter
        self._iter = ImageIter(batch_size, data_shape,
                               path_imgrec=path_imgrec,
                               path_imglist=path_imglist,
                               path_root=path_root, imglist=imglist,
                               shuffle=shuffle, aug_list=[],
                               data_name=data_name, label_name=label_name)
        self.data_shape = tuple(data_shape)
        self.max_objects = max_objects
        self.auglist = aug_list if aug_list is not None else \
            CreateDetAugmenter(data_shape, **kwargs)
        self.data_name = data_name
        self.label_name = label_name

    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        return [DataDesc(self.label_name,
                         (self.batch_size, self.max_objects, 5))]

    def reset(self):
        self._iter.reset()

    def _parse_label(self, raw):
        """Reference `detection.py _parse_label` convention: the label is
        [A, B, header..., objects...] where A = header width (counting A
        and B themselves), B = object record width >= 5; objects begin at
        raw[A:].  A flat multiple-of-5 list with no plausible header is
        accepted as bare [cls,x1,y1,x2,y2] rows for convenience."""
        raw = np.asarray(raw, np.float32).ravel()
        obj = None
        if raw.size >= 2:
            header = int(raw[0])
            width = int(raw[1])
            if (2 <= header <= raw.size and width >= 5
                    and float(header) == raw[0] and float(width) == raw[1]
                    and (raw.size - header) % width == 0):
                obj = raw[header:].reshape(-1, width)[:, :5]
        if obj is None:
            if raw.size % 5:
                raise MXNetError(
                    f"ImageDetIter: cannot parse label of size {raw.size} "
                    "(neither [A,B,header...,objects...] nor flat 5-wide)")
            obj = raw.reshape(-1, 5)
        out = np.full((self.max_objects, 5), -1.0, np.float32)
        n = min(len(obj), self.max_objects)
        out[:n] = obj[:n]
        return out

    def next(self):
        c, h, w = self.data_shape
        data = np.zeros((self.batch_size, c, h, w), np.float32)
        labels = np.full((self.batch_size, self.max_objects, 5), -1.0,
                         np.float32)
        i = 0
        pad = 0
        try:
            while i < self.batch_size:
                raw_label, buf = self._iter.next_sample()
                img = imdecode(buf)
                label = self._parse_label(raw_label)
                for aug in self.auglist:
                    img, label = aug(img, label)
                arr = img.asnumpy()
                data[i] = arr.transpose(2, 0, 1)
                labels[i] = label
                i += 1
        except StopIteration:
            if i == 0:
                raise
            pad = self.batch_size - i
        return DataBatch(data=[array(data)], label=[array(labels)],
                         pad=pad, provide_data=self.provide_data,
                         provide_label=self.provide_label)
