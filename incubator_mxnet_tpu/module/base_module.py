"""BaseModule with the classic fit/score/predict training loop
(reference `python/mxnet/module/base_module.py`, fit at :409,
train loop :515-560)."""
from __future__ import annotations

import logging
import time

import numpy as _np

from ..base import MXNetError
from .. import metric as _metric
from .. import io as _io
from ..model import BatchEndParam
from ..ndarray.ndarray import NDArray


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None
        self._total_exec_bytes = 0
        self._supervisor = None   # JobSupervisor of the last dist fit
        self._guardian = None     # TrainingGuardian of the current fit

    # -- high-level API --------------------------------------------------------
    def forward_backward(self, data_batch):
        """Reference `base_module.py:193 forward_backward`."""
        self.forward(data_batch, is_train=True)
        self.backward()

    def fit_step(self, data_batch, eval_metric):
        """One training step plus metric update.  Subclasses may override
        with a fused single-program implementation (Module does on TPU)."""
        self.forward_backward(data_batch)
        self.update()
        self.update_metric(eval_metric, data_batch.label)

    def _fit_block_k(self):
        """How many batches `fit` may hand to `fit_block` per dispatch.
        1 = classic per-batch stepping; Module returns K>1 when the fused
        K-step scan program is available (MXNET_FUSED_STEP_BLOCK)."""
        return 1

    def fit_block(self, data_batches, eval_metric):
        """Run a block of train steps in one dispatch when the subclass
        can (Module: `lax.scan` over K stacked batches).  Returns True when
        handled; False -> `fit` falls back to per-batch `fit_step`."""
        return False

    def _fit_block_cursor(self, j):
        """Hook: `fit` is about to fire batch j's callbacks for the last
        processed block (subclasses point per-batch output views at j)."""

    def check(self, hints=True):
        """Run the `mxlint` static graph passes over this module's Symbol
        (analysis/graph_passes.py) — duplicate names, dead outputs, aux
        races, f64 promotion, unbound inputs, TPU tile-alignment hints —
        seeded with the bound data/label shapes when available.  Returns
        an `analysis.Report`; raises nothing."""
        from .. import analysis as _analysis
        if self._symbol is None:
            return _analysis.Report(target=type(self).__name__)
        shapes = {}
        for desc in list(getattr(self, "_data_shapes", None) or []) + \
                list(getattr(self, "_label_shapes", None) or []):
            if hasattr(desc, "name"):
                shapes[desc.name] = tuple(desc.shape)
            else:
                shapes[desc[0]] = tuple(desc[1])
        return _analysis.check(self._symbol, shapes=shapes or None,
                               hints=hints,
                               target=self._symbol.name or "symbol")

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0, sparse_row_id_fn=None):
        """Reference `base_module.py score`."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)
        eval_metric.reset()
        actual_num_batch = 0
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                       eval_metric=eval_metric, locals=locals())
                for cb in _as_list(batch_end_callback):
                    cb(params)
            actual_num_batch += 1
        if score_end_callback:
            params = BatchEndParam(epoch=epoch, nbatch=actual_num_batch,
                                   eval_metric=eval_metric, locals=locals())
            for cb in _as_list(score_end_callback):
                cb(params)
        return eval_metric.get_name_value()

    def _infer_buckets(self, eval_data):
        """The shape buckets inference batches pad up to: the iterator's
        batch size (plus any bound data shape, which warmup compiled)."""
        buckets = set()
        bs = getattr(eval_data, "batch_size", 0) or 0
        if bs:
            buckets.add(int(bs))
        if self.binded and getattr(self, "_data_shapes", None):
            shape = self._data_shapes[0][1] if not hasattr(
                self._data_shapes[0], "shape") else self._data_shapes[0].shape
            if shape:
                buckets.add(int(shape[0]))
        return sorted(buckets)

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        buckets = self._infer_buckets(eval_data)
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            if buckets:
                # a ragged tail batch would force a fresh XLA compile
                # (analysis/recompile.py's shape-churn hazard); pad it to
                # the compiled bucket and slice the pad rows back off
                eval_batch = _io.pad_to_bucket(eval_batch, buckets)
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - (pad or 0)]
                       for out in self.get_outputs()]
            yield (outputs, nbatch, eval_batch)

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False,
                sparse_row_id_fn=None):
        """Reference `base_module.py predict`."""
        assert self.binded and self.params_initialized
        if isinstance(eval_data, (NDArray, _np.ndarray)):
            if isinstance(eval_data, _np.ndarray):
                from ..ndarray import array
                eval_data = array(eval_data)
            self.forward(_io.DataBatch([eval_data]))
            return self.get_outputs()[0]
        if reset:
            eval_data.reset()
        buckets = self._infer_buckets(eval_data)
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            if buckets:
                # pad the ragged tail to a compiled bucket instead of
                # recompiling for it (see iter_predict)
                eval_batch = _io.pad_to_bucket(eval_batch, buckets)
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - (pad or 0)].copy()
                       for out in self.get_outputs()]
            output_list.append(outputs)
        if len(output_list) == 0:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            for out in output_list:
                if len(out) != num_outputs:
                    raise ValueError("Cannot merge batches, as num of outputs "
                                     "is not the same in mini-batches.")
            from ..ndarray import concatenate
            output_list2 = [concatenate([out[i] for out in output_list])
                            for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return output_list2[0]
            return output_list2
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None, checkpoint_dir=None,
            checkpoint_period=100, checkpoint_keep_last=5, resume=False,
            max_restarts=None, mesh=None):
        """THE classic training loop (reference `base_module.py:409 fit`).

        Elastic checkpointing (no reference analogue): with
        ``checkpoint_dir`` set, every `checkpoint_period` processed
        batches an async snapshot of the FULL training state — params,
        optimizer slots, update counts, iterator position, RNG streams —
        is staged to pooled host buffers and committed atomically by a
        background thread while training continues; ``resume=True``
        restores the newest valid checkpoint and continues mid-epoch
        (train-metric accumulation restarts at the resumed batch).  A
        SIGTERM during fit triggers one final synchronous snapshot before
        exiting (checkpoint/manager.py preemption hook).

        Failover (resilience layer): when a distributed run loses a
        parameter server permanently (`ServerLostError` — crashed,
        partitioned past the retry budget, or restarted empty) and a
        ``checkpoint_dir`` is set, fit tears down the kvstore and
        restarts from the last committed checkpoint instead of dying, up
        to ``max_restarts`` times (default: MXNET_FIT_MAX_RESTARTS).  A
        replacement server must be reachable at the configured address —
        the restarted fit re-registers, re-pushes the checkpointed
        params, and re-ships the optimizer exactly like a fresh launch.
        The budget covers failures during the restart's own re-init too
        (the replacement server dying mid-handshake consumes a restart,
        not the whole run).

        Elastic supervision (resilience/supervisor.py): a multi-worker
        dist fit runs under a per-host `JobSupervisor` (MXNET_SUPERVISOR)
        — heartbeats to the coordinator, a watchdog around every sync
        push/pull/barrier, straggler findings.  A HOST loss then surfaces
        as a `CollectiveTimeoutError` naming the absent hosts instead of
        an indefinite hang, and with a ``checkpoint_dir`` set fit drives
        **shrink-and-resume**: the survivors agree on the new world size
        via the epoch-fenced shrink barrier, this worker adopts its new
        (dense) rank, and the run restarts from the last committed
        checkpoint at the smaller world size — a fenced-out stale host
        can never rejoin and corrupt the shrunk pod.

        Training guardian (resilience/guardian.py, MXNET_GUARDIAN): the
        fused step computes an in-graph health word (all-finite + grad
        norm) and refuses non-finite updates (**skip-batch**, positions
        quarantined); a diagnosed loss spike triggers
        **rollback-to-last-good** — the newest checkpoint whose manifest
        carries a healthy ``health`` stamp at or before the last
        in-bounds step is restored, the intervening good batches replay
        bit-identically, and the quarantined spike window is skipped;
        past the failure/rollback budget a structured
        `TrainingDivergedError` names the step, signal, and data shard.
        """
        import os as _os
        from ..resilience import ServerLostError, CollectiveTimeoutError
        from ..resilience import guardian as _guardian_mod
        if max_restarts is None:
            from .. import config as _config
            max_restarts = int(_config.get("MXNET_FIT_MAX_RESTARTS"))
        failed_over = False
        self._guardian = _guardian_mod.TrainingGuardian.maybe_create(
            checkpoint_dir, logger=self.logger)
        # every attempt gets the same fixed arguments; the restart loop
        # below only flips resume/force flags (one dict, not a second
        # copy of the parameter list to keep in sync)
        fixed = dict(
            eval_data=eval_data, eval_metric=eval_metric,
            epoch_end_callback=epoch_end_callback,
            batch_end_callback=batch_end_callback, kvstore=kvstore,
            optimizer=optimizer, optimizer_params=optimizer_params,
            eval_end_callback=eval_end_callback,
            eval_batch_end_callback=eval_batch_end_callback,
            initializer=initializer, arg_params=arg_params,
            aux_params=aux_params, allow_missing=allow_missing,
            begin_epoch=begin_epoch, num_epoch=num_epoch,
            validation_metric=validation_metric, monitor=monitor,
            sparse_row_id_fn=sparse_row_id_fn,
            checkpoint_dir=checkpoint_dir,
            checkpoint_period=checkpoint_period,
            checkpoint_keep_last=checkpoint_keep_last, mesh=mesh)
        while True:
            try:
                return self._fit_attempt(
                    train_data, force_rebind=force_rebind,
                    force_init=force_init, resume=resume, **fixed)
            except _guardian_mod.RollbackRequested as e:
                # the guardian diagnosed a loss spike whose update was
                # already applied: restore the newest HEALTHY checkpoint
                # at or before the last in-bounds step (the guardian's
                # pending_rollback_step bounds the resume selection) and
                # replay — the spike window itself is quarantined, so
                # the resumed run skips it.  Budgeted inside the
                # guardian: past MXNET_GUARDIAN_MAX_ROLLBACKS the spike
                # escalates to TrainingDivergedError instead.
                if checkpoint_dir is None or self._guardian is None:
                    raise
                self.logger.warning(
                    "fit: %s — restarting from the last healthy "
                    "checkpoint in %r", e, checkpoint_dir)
                self._teardown_kvstore()
                resume = True
                force_rebind = True
                force_init = True
            except (ServerLostError, CollectiveTimeoutError,
                    ConnectionError, EOFError, TimeoutError) as e:
                # raw connection/timeout errors are recoverable only on a
                # RESTART attempt's re-init (handshake against the
                # replacement server, before per-server breakers exist) —
                # on a first attempt they are real configuration errors
                if not isinstance(e, (ServerLostError,
                                      CollectiveTimeoutError)) \
                        and not failed_over:
                    raise
                if checkpoint_dir is None or max_restarts <= 0:
                    raise
                if not isinstance(kvstore, str):
                    # a caller-provided kvstore INSTANCE cannot be
                    # rebuilt; restarting would loop on its closed
                    # channels — surface the loss instead
                    raise
                if isinstance(e, CollectiveTimeoutError):
                    # a HOST (not a server) is gone: before restarting,
                    # the survivors must agree on the smaller world —
                    # the epoch-fenced shrink barrier.  This worker then
                    # adopts its new dense rank and the post-shrink
                    # membership epoch; the coordinator reset the kvstore
                    # state at commit, so the resumed attempt re-inits it
                    # from the checkpoint exactly like a fresh launch.
                    if self._supervisor is None:
                        raise
                    try:
                        shrink = self._supervisor.shrink(reason=str(e))
                    except Exception as shrink_exc:
                        self.logger.error(
                            "fit: shrink barrier failed (%s) after %s",
                            shrink_exc, e)
                        raise e from shrink_exc
                    self.logger.warning(
                        "fit: %s — pod shrunk to world_size=%d at epoch "
                        "%d (this worker: rank %d -> %d)", e,
                        shrink.world_size, shrink.epoch,
                        self._supervisor.rank, shrink.rank)
                    _os.environ["DMLC_RANK"] = str(shrink.rank)
                    _os.environ["DMLC_NUM_WORKER"] = str(shrink.world_size)
                    _os.environ["MXNET_SUPERVISOR_EPOCH"] = \
                        str(shrink.epoch)
                    self._supervisor = None
                    # the pre-shrink jax.distributed group still spans
                    # the dead host: tear it down so the restarted
                    # kvstore's collective plane re-initializes (and
                    # re-derives its worker mesh) at the surviving world
                    # size instead of failing against the stale group
                    # and silently degrading to the socket data plane.
                    # User code holding its own dp meshes re-derives
                    # them with parallel.mesh.rebuild().
                    try:
                        from ..dist import collective as _collective
                        _collective.shutdown()
                    except Exception:
                        pass
                max_restarts -= 1
                failed_over = True
                self.logger.warning(
                    "fit: %s — restarting from the last checkpoint in %r "
                    "(%d restart(s) remaining)", e, checkpoint_dir,
                    max_restarts)
                self._teardown_kvstore()
                # the next attempt resumes the checkpoints THIS run wrote
                # (when one exists, its params override everything);
                # caller-supplied arg_params stay in place as the
                # fallback — a crash BEFORE the first commit must restart
                # from the caller's (e.g. pretrained) weights, not from a
                # fresh initializer draw
                resume = True
                force_rebind = True
                force_init = True

    def _fit_attempt(self, train_data, eval_data=None, eval_metric="acc",
                     epoch_end_callback=None, batch_end_callback=None,
                     kvstore="local", optimizer="sgd",
                     optimizer_params=(("learning_rate", 0.01),),
                     eval_end_callback=None, eval_batch_end_callback=None,
                     initializer=None, arg_params=None, aux_params=None,
                     allow_missing=False, force_rebind=False,
                     force_init=False, begin_epoch=0, num_epoch=None,
                     validation_metric=None, monitor=None,
                     sparse_row_id_fn=None, checkpoint_dir=None,
                     checkpoint_period=100, checkpoint_keep_last=5,
                     resume=False, mesh=None):
        """One fit attempt; `ServerLostError` propagates to `fit`'s
        restart loop with the checkpoint manager already flushed/closed."""
        assert num_epoch is not None, "please specify number of epochs"
        from ..initializer import Uniform
        if initializer is None:
            initializer = Uniform(0.01)

        ckpt_mgr = None
        ckpt_resume = None
        resume_nbatch = 0
        gstep = 0
        if checkpoint_dir is not None:
            from .. import checkpoint as _ckpt
            from .. import config as _config
            if _config.get("MXNET_PROGRAM_CACHE"):
                # a prior run's programs/ payload: compiled executables
                # this attempt can load instead of recompiling (the
                # cold-start half of elastic restart; compile/ subsystem)
                import os as _os
                from .. import compile as _compile
                _compile.add_source(_os.path.join(checkpoint_dir,
                                                  "programs"))
            if resume:
                # read-only: the manager (writer, retention, rank layout)
                # is built AFTER init_optimizer, when the kvstore — and
                # with it this process's rank — is known
                g = getattr(self, "_guardian", None)
                if g is not None and g.pending_rollback_step is not None:
                    # rollback-to-last-good: newer checkpoints may carry
                    # the spike's damage — select by health stamp AND
                    # the guardian's last in-bounds step
                    path = _ckpt.latest_healthy(
                        checkpoint_dir, max_step=g.pending_rollback_step)
                else:
                    path = _ckpt.latest(checkpoint_dir)
                ckpt_resume = _ckpt.load(path) if path is not None else None
            elif _ckpt.latest(checkpoint_dir, deep=False,
                              include_rejected=True) is not None:
                # include_rejected: even a directory holding ONLY
                # canary-rejected checkpoints belongs to some other run
                # a fresh run must not share a directory with an old run's
                # checkpoints: the old run's higher step numbers would win
                # `latest()` after this run's first crash and resume would
                # silently continue the ABANDONED run
                raise MXNetError(
                    f"checkpoint_dir {checkpoint_dir!r} already holds "
                    "checkpoints from a previous run; pass resume=True to "
                    "continue it, or point a fresh run at a fresh "
                    "directory (or delete the old checkpoints)")
            if ckpt_resume is not None:
                self.logger.info("resuming from %s (step %d, epoch %d, "
                                 "batch %d)", ckpt_resume.path,
                                 ckpt_resume.step, ckpt_resume.epoch,
                                 ckpt_resume.nbatch)
                arg_params, aux_params = _ckpt.state.split_params(
                    ckpt_resume.arrays)
                allow_missing = False
                force_init = True
                begin_epoch = ckpt_resume.epoch
                resume_nbatch = ckpt_resume.nbatch
                gstep = ckpt_resume.step

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params, mesh=mesh)
        sup = self._start_supervisor()
        # h2d staging ring (io_plane.py, MXNET_IO_RING): wrap the
        # training iterator so batches decode, stage into reusable host
        # buffers, and transfer on the mx-io-h2d thread WHILE the
        # current step computes — the fit loop pops device-resident
        # batches and never blocks on device_put.  Wrapped here (after
        # init_optimizer) so the fused step's exact staging target —
        # data sharding + per-input dtypes — binds the ring; checkpoint
        # capture, guardian quarantine and seek all delegate through.
        train_data, io_ring = self._wrap_io_ring(train_data)
        if checkpoint_dir is not None:
            from .. import checkpoint as _ckpt
            # dist layout: the resolved kvstore names this process's rank —
            # rank 0 owns params/manifest/retention, other ranks publish
            # side shards only (checkpoint/manager.py dist layout)
            kv = getattr(self, "_kvstore", None)
            rank = getattr(kv, "rank", 0) if kv is not None else 0
            num_ranks = getattr(kv, "num_workers", 1) if kv is not None \
                else 1
            ckpt_mgr = _ckpt.CheckpointManager(
                checkpoint_dir, keep_last=checkpoint_keep_last,
                rank=rank, num_ranks=num_ranks)
            if ckpt_resume is not None and rank != 0:
                # this worker's rank-local state (its own iterator
                # position/permutation, RNG streams) lives in ITS shard;
                # rank 0's blobs must not stand in for it — absent a shard
                # (lagging rank at commit time) fall back to position-only
                # resume via the manifest's nbatch
                ckpt_resume.blobs.pop(_ckpt.state.ITERATOR_BLOB, None)
                ckpt_resume.rng = None
                shard = ckpt_resume.rank_shard(rank)
                if shard is not None:
                    ckpt_resume.blobs.update(shard.get("blobs") or {})
                    ckpt_resume.rng = shard.get("rng")
        if ckpt_resume is not None:
            from .. import checkpoint as _ckpt
            _ckpt.state.restore_module_optimizer(
                self, ckpt_resume.blobs.get(_ckpt.state.OPTIMIZER_BLOB))
            _ckpt.state.restore_rng(ckpt_resume.rng)
        guardian = getattr(self, "_guardian", None)
        if guardian is not None:
            if guardian.pending_rollback_step is not None:
                # the restore landed (or no healthy checkpoint existed
                # and this attempt restarts from the caller's params) —
                # either way the rollback is committed and the spike
                # detector's history starts fresh
                guardian.rollback_committed(
                    ckpt_resume.step if ckpt_resume is not None else 0)
            # attach AFTER every fused-step rebuild path (init_optimizer
            # and the optimizer-state restore both construct fresh ones)
            guardian.attach(self)
            guardian.attach_iterator(train_data)
        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)

        last_snap_step = gstep
        if ckpt_mgr is not None:
            ckpt_mgr.install_preemption_hook()
        from .. import analysis as _analysis
        from ..resilience import CollectiveTimeoutError, ServerLostError
        server_lost = False
        try:
            with _analysis.hostsync.hot_loop("Module.fit"):
                self._fit_epochs(
                    train_data, eval_data, eval_metric, validation_metric,
                    epoch_end_callback, batch_end_callback,
                    eval_end_callback, eval_batch_end_callback, monitor,
                    sparse_row_id_fn, begin_epoch, num_epoch, ckpt_mgr,
                    ckpt_resume, resume_nbatch, gstep, last_snap_step,
                    checkpoint_period)
        except (ServerLostError, CollectiveTimeoutError):
            server_lost = True   # either failover signal must not be
            raise                # masked by a deferred flush error
        finally:
            if io_ring is not None:
                # stop the feeder thread and drop read-ahead; the INNER
                # iterator stays usable for the caller/restart loop
                try:
                    io_ring._pause()
                except Exception:
                    pass
            if sup is not None:
                # stop the heartbeat loop but KEEP self._supervisor: the
                # restart loop's shrink barrier still needs its identity
                # and membership view (the shrink request rides a fresh
                # channel, not the stopped heartbeat one)
                from ..resilience import supervisor as _sup_mod
                _sup_mod.deactivate(sup)
                try:
                    sup.stop()
                except Exception:
                    pass
            if ckpt_mgr is not None:
                try:
                    ckpt_mgr.flush()
                except MXNetError:
                    # a deferred background-write error must not mask the
                    # failover signal the restart loop keys on
                    if not server_lost:
                        raise
                finally:
                    ckpt_mgr.close()

    def _wrap_io_ring(self, train_data):
        """Wrap the training iterator with the h2d staging ring
        (io_plane.DevicePrefetchIter) when MXNET_IO_RING is on and a
        fused train step is live to provide the placement.  Returns
        ``(iterator, ring_or_None)``; the caller closes the ring when
        the attempt ends."""
        from .. import config as _config
        fs = getattr(self, "_fused_step", None)
        if fs is None or getattr(fs, "broken", False) or \
                not _config.get("MXNET_IO_RING"):
            return train_data, None
        from .. import io_plane as _io_plane
        if isinstance(train_data, _io_plane.DevicePrefetchIter):
            return train_data, None
        if not hasattr(train_data, "next") or \
                not hasattr(train_data, "reset"):
            return train_data, None   # a bare iterable: leave it alone
        try:
            wrapped = _io_plane.DevicePrefetchIter(
                train_data, placement=fs.ring_placement, name="fit")
        except Exception as e:
            self.logger.warning(
                "h2d ring unavailable (%s); using the blocking input "
                "path", str(e)[:200])
            return train_data, None
        return wrapped, wrapped

    def _start_supervisor(self):
        """Attach a `JobSupervisor` to a multi-worker dist fit: heartbeat
        this host into the coordinator's membership table and arm the
        hung-collective watchdog around the kvstore's sync exchanges.
        Returns the started supervisor (also kept on `self._supervisor`
        for the restart loop's shrink barrier) or None — single-process
        and non-dist runs never pay for supervision, and a supervisor
        bring-up failure degrades to the unsupervised PR 5 behavior
        instead of blocking training."""
        self._supervisor = None
        kv = getattr(self, "_kvstore", None)
        if kv is None or getattr(kv, "num_workers", 1) <= 1 or \
                not hasattr(kv, "_chan"):
            return None
        from .. import config as _config
        if not _config.get("MXNET_SUPERVISOR"):
            return None
        from ..resilience import supervisor as _sup_mod
        try:
            sup = _sup_mod.JobSupervisor.for_kvstore(kv).start()
        except Exception as e:
            self.logger.warning(
                "supervisor unavailable (%s); continuing unsupervised",
                str(e)[:200])
            return None
        _sup_mod.activate(sup)
        self._supervisor = sup
        return sup

    def _teardown_kvstore(self):
        """Drop the current kvstore connection so the next
        `init_optimizer` builds a fresh one (the failover restart path).
        No protocol 'stop' is sent: this worker is RESTARTING, not
        leaving — a 'stop' would count toward the surviving servers'
        shutdown quorum and take them down under the resumed run."""
        kv = getattr(self, "_kvstore", None)
        if kv is not None:
            try:
                if getattr(kv, "_chans", None) is not None:
                    kv.close(send_stop=False)
                else:
                    kv.close()
            except Exception:
                pass
        self._kvstore = None
        self.optimizer_initialized = False

    def _fit_epochs(self, train_data, eval_data, eval_metric,
                    validation_metric, epoch_end_callback,
                    batch_end_callback, eval_end_callback,
                    eval_batch_end_callback, monitor, sparse_row_id_fn,
                    begin_epoch, num_epoch, ckpt_mgr, ckpt_resume,
                    resume_nbatch, gstep, last_snap_step, checkpoint_period):
        from ..resilience import faults as _faults
        guardian = getattr(self, "_guardian", None)
        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            nbatch = 0
            if ckpt_resume is not None and epoch == begin_epoch:
                # continue mid-epoch: native iterator restore (shuffle
                # permutation + position) where supported, reset+skip
                # otherwise; metric accumulation restarts here
                from .. import checkpoint as _ckpt
                _ckpt.state.restore_iterator(
                    train_data,
                    ckpt_resume.blobs.get(_ckpt.state.ITERATOR_BLOB),
                    resume_nbatch)
                nbatch = resume_nbatch
            data_iter = iter(train_data)
            end_of_batch = False
            try:
                next_data_batch = next(data_iter)
            except StopIteration:
                end_of_batch = True
                next_data_batch = None
            while not end_of_batch:
                if guardian is not None and \
                        guardian.should_skip(epoch, nbatch):
                    # quarantined stream position: consume it, never
                    # train on it — the position still advances so
                    # resume bookkeeping stays aligned with the run
                    # that wrote the quarantine entry
                    guardian.note_skipped(epoch, nbatch)
                    nbatch += 1
                    try:
                        next_data_batch = next(data_iter)
                    except StopIteration:
                        end_of_batch = True
                    continue
                # pod chaos site: a `kill` here is a whole-host SIGKILL
                # at a step boundary (the membership deadline detects it,
                # the survivors' watchdogs convert the stalled round)
                _faults.fire("host.step", nbatch=nbatch, epoch=epoch)
                step_tic = time.time()
                data_batch = next_data_batch
                nbatch_at_entry = nbatch
                # block mode: collect K batches and let the subclass run
                # them as ONE dispatch (Module: lax.scan over K stacked
                # batches — host bookkeeping amortizes across the block).
                # Callbacks still fire once per batch, in bursts of K.
                block = [data_batch]
                block_k = 1 if monitor is not None else self._fit_block_k()
                while len(block) < block_k and not end_of_batch:
                    if guardian is not None and guardian.should_skip(
                            epoch, nbatch_at_entry + len(block)):
                        # a quarantined position mid-block: stop the
                        # block before it (it becomes the next head and
                        # the loop-top skip consumes it)
                        break
                    try:
                        block.append(next(data_iter))
                    except StopIteration:
                        end_of_batch = True
                burst = ()
                if monitor is not None:
                    # monitoring needs per-pass intermediate values: use the
                    # unfused forward/backward so the hooks can observe them
                    monitor.tic()
                    self.forward_backward(data_batch)
                    self.update()
                    burst = block   # single batch; callback fires below
                elif len(block) == block_k and block_k > 1 and \
                        self.fit_block(block, eval_metric):
                    burst = block   # one scan dispatch; callbacks burst
                else:
                    # classic per-batch stepping with classic callback
                    # timing (the tail of an epoch, or a block the fused
                    # path rejected — e.g. a host-side metric, where a
                    # deferred burst would hand batch-j callbacks block-
                    # final metric/output state for no fusion benefit)
                    for b in block:
                        self.fit_step(b, eval_metric)
                        if batch_end_callback is not None:
                            batch_end_params = BatchEndParam(
                                epoch=epoch, nbatch=nbatch,
                                eval_metric=eval_metric, locals=locals())
                            for callback in _as_list(batch_end_callback):
                                callback(batch_end_params)
                        nbatch += 1
                if not end_of_batch:
                    try:
                        next_data_batch = next(data_iter)
                        self.prepare(next_data_batch,
                                     sparse_row_id_fn=sparse_row_id_fn)
                    except StopIteration:
                        end_of_batch = True
                if monitor is not None:
                    self.update_metric(eval_metric, data_batch.label)
                    monitor.toc_print()
                for _bi, _b in enumerate(burst):
                    self._fit_block_cursor(_bi)
                    if batch_end_callback is not None:
                        batch_end_params = BatchEndParam(
                            epoch=epoch, nbatch=nbatch,
                            eval_metric=eval_metric, locals=locals())
                        for callback in _as_list(batch_end_callback):
                            callback(batch_end_params)
                    nbatch += 1

                gstep += nbatch - nbatch_at_entry
                if guardian is not None and nbatch > nbatch_at_entry:
                    # pair the block's health tokens with their stream
                    # positions, then run the policy ladder every
                    # MXNET_GUARDIAN_INTERVAL steps (one device gather;
                    # raises RollbackRequested / TrainingDivergedError)
                    guardian.tag(epoch, nbatch_at_entry, train_data)
                    guardian.maybe_poll(gstep)
                if self._supervisor is not None and nbatch > nbatch_at_entry:
                    # per-step wall time feeds the heartbeat EWMA the
                    # coordinator's straggler detection compares across
                    # the pod; the step counter keys lag detection
                    self._supervisor.record_step(
                        (time.time() - step_tic) /
                        (nbatch - nbatch_at_entry))
                if ckpt_mgr is not None and nbatch > nbatch_at_entry:
                    # batch boundary: params and (epoch, nbatch, step)
                    # agree — the only place a snapshot may be taken
                    ckpt_mgr.honor_preemption(
                        lambda: self._elastic_snapshot(
                            ckpt_mgr, train_data, epoch, nbatch, gstep,
                            sync=True, meta={"preempted": True}))
                    if gstep - last_snap_step >= checkpoint_period:
                        self._elastic_snapshot(ckpt_mgr, train_data, epoch,
                                               nbatch, gstep)
                        last_snap_step = gstep

            if guardian is not None:
                # drain the tail of the epoch's health tokens before the
                # boundary snapshot stamps its manifest
                guardian.maybe_poll(gstep, force=True)
            # epoch boundary: eval scoring, param syncs, callbacks and
            # snapshots legitimately block once per epoch — not hot-loop
            # host-sync hazards (analysis.hostsync would misattribute)
            from .. import analysis as _analysis
            with _analysis.hostsync.paused():
                for name, val in eval_metric.get_name_value():
                    self.logger.info("Epoch[%d] Train-%s=%f", epoch, name,
                                     val)
                toc = time.time()
                self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                                 (toc - tic))

                arg_params_, aux_params_ = self.get_params()
                self.set_params(arg_params_, aux_params_)

                if epoch_end_callback is not None:
                    for callback in _as_list(epoch_end_callback):
                        callback(epoch, self.symbol, arg_params_,
                                 aux_params_)

                if eval_data:
                    res = self.score(
                        eval_data, validation_metric,
                        score_end_callback=eval_end_callback,
                        batch_end_callback=eval_batch_end_callback,
                        epoch=epoch)
                    for name, val in res:
                        self.logger.info("Epoch[%d] Validation-%s=%f",
                                         epoch, name, val)
                train_data.reset()
                if ckpt_mgr is not None:
                    # epoch-boundary snapshot AFTER the reset so the fresh
                    # shuffle permutation travels with it: resume starts
                    # the next epoch exactly as this run would have
                    self._elastic_snapshot(ckpt_mgr, train_data, epoch + 1,
                                           0, gstep)
                    last_snap_step = gstep
                    ckpt_mgr.honor_preemption(
                        lambda: self._elastic_snapshot(
                            ckpt_mgr, train_data, epoch + 1, 0, gstep,
                            sync=True, meta={"preempted": True}))

    def _elastic_snapshot(self, mgr, train_data, epoch, nbatch, step,
                          sync=False, meta=None):
        """Stage one elastic checkpoint: sync device->pooled-host gather,
        background serialization + atomic commit (checkpoint/)."""
        from .. import analysis as _analysis
        with _analysis.hostsync.paused():
            return self._elastic_snapshot_impl(mgr, train_data, epoch,
                                               nbatch, step, sync=sync,
                                               meta=meta)

    def _elastic_snapshot_impl(self, mgr, train_data, epoch, nbatch, step,
                               sync=False, meta=None):
        """Checkpoint gathers block by design — not hot-loop host syncs
        (hence the `paused()` wrapper above)."""
        from .. import checkpoint as _ckpt
        guardian = getattr(self, "_guardian", None)
        if guardian is not None:
            # drain pending health tokens FIRST: a snapshot must never
            # stamp itself healthy on stale evidence (an undetected
            # spike raises here and the snapshot is not taken at all)
            guardian.maybe_poll(step, force=True)
            meta = dict(meta or {}, health=guardian.health_stamp())
        if mgr.rank != 0:
            # non-primary ranks publish ONLY rank-local state (this
            # worker's iterator position/permutation; its updater slots
            # when the optimizer runs worker-side) as a side shard —
            # params are identical across ranks and a server-side
            # optimizer's slots are rank 0's to pull, so gathering either
            # here would multiply checkpoint cost by the worker count for
            # bytes that are thrown away
            blobs = {}
            if self.optimizer_initialized and \
                    not getattr(self, "_update_on_kvstore", False) and \
                    getattr(self, "_updater", None) is not None:
                blobs[_ckpt.state.OPTIMIZER_BLOB] = \
                    self._updater.get_states(dump_optimizer=True)
            it_blob = _ckpt.state.capture_iterator(train_data)
            if it_blob is not None:
                blobs[_ckpt.state.ITERATOR_BLOB] = it_blob
            mgr.snapshot(arrays={}, blobs=blobs, step=step, epoch=epoch,
                         nbatch=nbatch, sync=sync, meta=meta)
            return
        arrays, blobs = _ckpt.state.capture_module(self, train_data)
        meta = dict(meta or {})
        optimizer = getattr(self, "_optimizer", None)
        if optimizer is not None:
            # scalar optimizer position in the manifest (human-inspectable
            # evidence; the authoritative tensors ride the optimizer blob)
            meta["optimizer"] = optimizer.state_dict()
        mgr.snapshot(arrays=arrays, blobs=blobs, step=step, epoch=epoch,
                     nbatch=nbatch, sync=sync, meta=meta)
        self._export_checkpoint_programs(mgr)

    def _export_checkpoint_programs(self, mgr):
        """Ship the fused step's compiled executables as a ``programs/``
        payload next to the checkpoints, so a resumed (or freshly
        served) process loads programs from disk instead of recompiling
        (compile/ subsystem).  Entries are individually CRC'd and
        atomically published — a torn payload degrades to a recompile,
        never to a bad resume — and already-exported entries are
        skipped, so the steady-state cost is a directory stat."""
        from .. import config as _config
        if not _config.get("MXNET_PROGRAM_CACHE") or \
                not _config.get("MXNET_PROGRAM_CACHE_CHECKPOINT"):
            return
        fs = getattr(self, "_fused_step", None)
        if fs is None or getattr(fs, "broken", False):
            return
        import os
        try:
            fs.export_programs(os.path.join(mgr.directory, "programs"))
        except Exception as e:
            # payload is an optimization, never worth failing a snapshot
            self.logger.debug("program payload export skipped (%s)",
                              str(e)[:200])

    # -- properties / abstract -------------------------------------------------
    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self):
        raise NotImplementedError()

    @property
    def output_names(self):
        raise NotImplementedError()

    @property
    def data_shapes(self):
        raise NotImplementedError()

    @property
    def label_shapes(self):
        raise NotImplementedError()

    @property
    def output_shapes(self):
        raise NotImplementedError()

    def get_params(self):
        raise NotImplementedError()

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        raise NotImplementedError()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def save_params(self, fname):
        arg_params, aux_params = self.get_params()
        save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
        save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})
        from ..ndarray import save
        save(fname, save_dict)

    def load_params(self, fname):
        from ..ndarray import load
        save_dict = load(fname)
        arg_params = {}
        aux_params = {}
        for k, value in save_dict.items():
            arg_type, name = k.split(":", 1)
            if arg_type == "arg":
                arg_params[name] = value
            elif arg_type == "aux":
                aux_params[name] = value
            else:
                raise ValueError(f"Invalid param file {fname}")
        self.set_params(arg_params, aux_params)

    def install_monitor(self, mon):
        raise NotImplementedError()

    def prepare(self, data_batch, sparse_row_id_fn=None):
        pass

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError()

    def backward(self, out_grads=None):
        raise NotImplementedError()

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError()

    def update(self):
        raise NotImplementedError()

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError()

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False, mesh=None):
        raise NotImplementedError()


def _as_list(obj):
    if isinstance(obj, (list, tuple)):
        return obj
    return [obj]
