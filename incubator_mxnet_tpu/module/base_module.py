"""BaseModule with the classic fit/score/predict training loop
(reference `python/mxnet/module/base_module.py`, fit at :409,
train loop :515-560)."""
from __future__ import annotations

import logging
import time

import numpy as _np

from ..base import MXNetError
from .. import metric as _metric
from .. import io as _io
from ..model import BatchEndParam
from ..ndarray.ndarray import NDArray


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None
        self._total_exec_bytes = 0

    # -- high-level API --------------------------------------------------------
    def forward_backward(self, data_batch):
        """Reference `base_module.py:193 forward_backward`."""
        self.forward(data_batch, is_train=True)
        self.backward()

    def fit_step(self, data_batch, eval_metric):
        """One training step plus metric update.  Subclasses may override
        with a fused single-program implementation (Module does on TPU)."""
        self.forward_backward(data_batch)
        self.update()
        self.update_metric(eval_metric, data_batch.label)

    def _fit_block_k(self):
        """How many batches `fit` may hand to `fit_block` per dispatch.
        1 = classic per-batch stepping; Module returns K>1 when the fused
        K-step scan program is available (MXNET_FUSED_STEP_BLOCK)."""
        return 1

    def fit_block(self, data_batches, eval_metric):
        """Run a block of train steps in one dispatch when the subclass
        can (Module: `lax.scan` over K stacked batches).  Returns True when
        handled; False -> `fit` falls back to per-batch `fit_step`."""
        return False

    def _fit_block_cursor(self, j):
        """Hook: `fit` is about to fire batch j's callbacks for the last
        processed block (subclasses point per-batch output views at j)."""

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0, sparse_row_id_fn=None):
        """Reference `base_module.py score`."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)
        eval_metric.reset()
        actual_num_batch = 0
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                       eval_metric=eval_metric, locals=locals())
                for cb in _as_list(batch_end_callback):
                    cb(params)
            actual_num_batch += 1
        if score_end_callback:
            params = BatchEndParam(epoch=epoch, nbatch=actual_num_batch,
                                   eval_metric=eval_metric, locals=locals())
            for cb in _as_list(score_end_callback):
                cb(params)
        return eval_metric.get_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - (pad or 0)]
                       for out in self.get_outputs()]
            yield (outputs, nbatch, eval_batch)

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False,
                sparse_row_id_fn=None):
        """Reference `base_module.py predict`."""
        assert self.binded and self.params_initialized
        if isinstance(eval_data, (NDArray, _np.ndarray)):
            if isinstance(eval_data, _np.ndarray):
                from ..ndarray import array
                eval_data = array(eval_data)
            self.forward(_io.DataBatch([eval_data]))
            return self.get_outputs()[0]
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - (pad or 0)].copy()
                       for out in self.get_outputs()]
            output_list.append(outputs)
        if len(output_list) == 0:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            for out in output_list:
                if len(out) != num_outputs:
                    raise ValueError("Cannot merge batches, as num of outputs "
                                     "is not the same in mini-batches.")
            from ..ndarray import concatenate
            output_list2 = [concatenate([out[i] for out in output_list])
                            for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return output_list2[0]
            return output_list2
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None):
        """THE classic training loop (reference `base_module.py:409 fit`)."""
        assert num_epoch is not None, "please specify number of epochs"
        from ..initializer import Uniform
        if initializer is None:
            initializer = Uniform(0.01)

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)

        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            nbatch = 0
            data_iter = iter(train_data)
            end_of_batch = False
            next_data_batch = next(data_iter)
            while not end_of_batch:
                data_batch = next_data_batch
                # block mode: collect K batches and let the subclass run
                # them as ONE dispatch (Module: lax.scan over K stacked
                # batches — host bookkeeping amortizes across the block).
                # Callbacks still fire once per batch, in bursts of K.
                block = [data_batch]
                block_k = 1 if monitor is not None else self._fit_block_k()
                while len(block) < block_k and not end_of_batch:
                    try:
                        block.append(next(data_iter))
                    except StopIteration:
                        end_of_batch = True
                burst = ()
                if monitor is not None:
                    # monitoring needs per-pass intermediate values: use the
                    # unfused forward/backward so the hooks can observe them
                    monitor.tic()
                    self.forward_backward(data_batch)
                    self.update()
                    burst = block   # single batch; callback fires below
                elif len(block) == block_k and block_k > 1 and \
                        self.fit_block(block, eval_metric):
                    burst = block   # one scan dispatch; callbacks burst
                else:
                    # classic per-batch stepping with classic callback
                    # timing (the tail of an epoch, or a block the fused
                    # path rejected — e.g. a host-side metric, where a
                    # deferred burst would hand batch-j callbacks block-
                    # final metric/output state for no fusion benefit)
                    for b in block:
                        self.fit_step(b, eval_metric)
                        if batch_end_callback is not None:
                            batch_end_params = BatchEndParam(
                                epoch=epoch, nbatch=nbatch,
                                eval_metric=eval_metric, locals=locals())
                            for callback in _as_list(batch_end_callback):
                                callback(batch_end_params)
                        nbatch += 1
                if not end_of_batch:
                    try:
                        next_data_batch = next(data_iter)
                        self.prepare(next_data_batch,
                                     sparse_row_id_fn=sparse_row_id_fn)
                    except StopIteration:
                        end_of_batch = True
                if monitor is not None:
                    self.update_metric(eval_metric, data_batch.label)
                    monitor.toc_print()
                for _bi, _b in enumerate(burst):
                    self._fit_block_cursor(_bi)
                    if batch_end_callback is not None:
                        batch_end_params = BatchEndParam(
                            epoch=epoch, nbatch=nbatch,
                            eval_metric=eval_metric, locals=locals())
                        for callback in _as_list(batch_end_callback):
                            callback(batch_end_params)
                    nbatch += 1

            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            toc = time.time()
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch, (toc - tic))

            arg_params_, aux_params_ = self.get_params()
            self.set_params(arg_params_, aux_params_)

            if epoch_end_callback is not None:
                for callback in _as_list(epoch_end_callback):
                    callback(epoch, self.symbol, arg_params_, aux_params_)

            if eval_data:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)
            train_data.reset()

    # -- properties / abstract -------------------------------------------------
    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self):
        raise NotImplementedError()

    @property
    def output_names(self):
        raise NotImplementedError()

    @property
    def data_shapes(self):
        raise NotImplementedError()

    @property
    def label_shapes(self):
        raise NotImplementedError()

    @property
    def output_shapes(self):
        raise NotImplementedError()

    def get_params(self):
        raise NotImplementedError()

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        raise NotImplementedError()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def save_params(self, fname):
        arg_params, aux_params = self.get_params()
        save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
        save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})
        from ..ndarray import save
        save(fname, save_dict)

    def load_params(self, fname):
        from ..ndarray import load
        save_dict = load(fname)
        arg_params = {}
        aux_params = {}
        for k, value in save_dict.items():
            arg_type, name = k.split(":", 1)
            if arg_type == "arg":
                arg_params[name] = value
            elif arg_type == "aux":
                aux_params[name] = value
            else:
                raise ValueError(f"Invalid param file {fname}")
        self.set_params(arg_params, aux_params)

    def install_monitor(self, mon):
        raise NotImplementedError()

    def prepare(self, data_batch, sparse_row_id_fn=None):
        pass

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError()

    def backward(self, out_grads=None):
        raise NotImplementedError()

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError()

    def update(self):
        raise NotImplementedError()

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError()

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError()


def _as_list(obj):
    if isinstance(obj, (list, tuple)):
        return obj
    return [obj]
