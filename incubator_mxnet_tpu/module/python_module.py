"""Python-implemented modules (reference
`python/mxnet/module/python_module.py`): plug arbitrary host-side
computation into a module chain (SequentialModule) without a Symbol.

`PythonModule` stubs the full BaseModule API for parameter-less modules;
`PythonLossModule` turns scores into a loss head whose gradient is
supplied by a user `grad_func` — useful for losses that are easier to
write against numpy than as graph ops.  Everything here is host-side by
design; compute-heavy custom logic belongs in a CustomOp (operator.py)
or a Pallas subgraph instead.
"""
from __future__ import annotations

import logging

from ..initializer import Uniform
from .. import ndarray as nd
from ..ndarray.ndarray import NDArray
from .base_module import BaseModule

__all__ = ["PythonModule", "PythonLossModule"]


class PythonModule(BaseModule):
    """A module whose API surface is implemented as convenient no-ops
    (reference `python_module.py:28`).  Subclasses override the pieces
    they need; parameter-less modules get bind/init/update for free."""

    def __init__(self, data_names, label_names, output_names,
                 logger=logging):
        super().__init__(logger=logger)
        if isinstance(data_names, tuple):
            data_names = list(data_names)
        if isinstance(label_names, tuple):
            label_names = list(label_names)
        self._data_names = data_names
        self._label_names = label_names
        self._output_names = output_names
        self._data_shapes = None
        self._label_shapes = None
        self._output_shapes = None

    # -- properties -----------------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._output_shapes

    # -- params ---------------------------------------------------------------
    def get_params(self):
        """A parameter-less module returns empty dicts (override if the
        subclass holds parameters)."""
        return ({}, {})

    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        if initializer is None:
            initializer = Uniform(0.01)
        self.params_initialized = True

    def update(self):
        """No parameters to update by default."""

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        """Evaluates outputs against labels; parameter-less pass-through
        modules often need nothing here (override if the module's outputs
        feed a metric)."""
        if self._label_shapes is None:
            return
        eval_metric.update(labels, self.get_outputs())

    # -- bind -----------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """Record shapes; there are no executors to allocate."""
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes
        self._output_shapes = self._compute_output_shapes()

    def _compute_output_shapes(self):
        """Subclasses define their output shapes from the bound inputs."""
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False, mesh=None):
        """Nothing to optimize by default."""
        self.optimizer_initialized = True


class PythonLossModule(PythonModule):
    """A loss head in Python (reference `python_module.py:243`): forward
    keeps the incoming scores, backward asks `grad_func(scores, labels)`
    for d(loss)/d(scores)."""

    def __init__(self, name="pyloss", data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 grad_func=None):
        super().__init__(data_names, label_names, [name + "_output"],
                         logger=logger)
        self._name = name
        assert len(data_names) == 1
        assert len(label_names) == 1
        self._scores = None
        self._labels = None
        self._scores_grad = None
        if grad_func is not None:
            assert callable(grad_func)
        self._grad_func = grad_func

    def _compute_output_shapes(self):
        # a loss head echoes its scores
        return [(self._name + "_output", self._data_shapes[0][1])]

    def forward(self, data_batch, is_train=None):
        self._scores = data_batch.data[0]
        if is_train is None:
            is_train = self.for_training
        if is_train:
            self._labels = data_batch.label[0]

    def get_outputs(self, merge_multi_context=True):
        assert merge_multi_context is True
        return [self._scores]

    def backward(self, out_grads=None):
        assert out_grads is None, \
            "For a loss module, out_grads should be None"
        assert self.for_training
        self._backward_impl()

    def _backward_impl(self):
        if self._grad_func is None:
            raise NotImplementedError(
                "PythonLossModule: pass grad_func or override "
                "_backward_impl")
        grad = self._grad_func(self._scores, self._labels)
        if not isinstance(grad, NDArray):
            grad = nd.array(grad)
        self._scores_grad = grad

    def get_input_grads(self, merge_multi_context=True):
        assert merge_multi_context is True
        return [self._scores_grad]

    def install_monitor(self, mon):
        raise NotImplementedError()
