"""DataParallelExecutorGroup (reference `python/mxnet/module/executor_group.py:143`).

Static batch slicing over devices (`decide_slices`, reference :281): each
context gets one Executor bound to its batch shard; gradients are reduced by
the kvstore / local updater.  On TPU the preferred large-scale path is the
mesh (`parallel/`), but this group preserves the reference's multi-device
training semantics for Module users.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from ..io import DataDesc
from ..ndarray.ndarray import NDArray
from ..ndarray import ndarray as _nd
from .. import ndarray as nd


def _split_input_slice(batch_size, work_load_list):
    """Reference `executor_group.py decide_slices` even split."""
    total = sum(work_load_list)
    slices = []
    start = 0
    for i, w in enumerate(work_load_list):
        if i == len(work_load_list) - 1:
            end = batch_size
        else:
            end = start + int(round(batch_size * w / total))
        slices.append(slice(start, end))
        start = end
    return slices


class DataParallelExecutorGroup:
    def __init__(self, symbol, contexts, workload, data_shapes, label_shapes,
                 param_names, for_training, inputs_need_grad, shared_group=None,
                 logger=None, fixed_param_names=None, grad_req="write",
                 state_names=None):
        self.symbol = symbol
        self.contexts = contexts
        self.workload = workload if workload else [1] * len(contexts)
        self.param_names = param_names
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.fixed_param_names = set(fixed_param_names or [])
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()

        self.data_shapes = [d if isinstance(d, DataDesc) else DataDesc(*d)
                            for d in data_shapes]
        self.label_shapes = [l if isinstance(l, DataDesc) else DataDesc(*l)
                             for l in (label_shapes or [])]
        self.data_names = [d.name for d in self.data_shapes]
        self.label_names = [l.name for l in self.label_shapes]

        batch_size = self.data_shapes[0].shape[0]
        self.batch_size = batch_size
        self.slices = _split_input_slice(batch_size, self.workload)

        if isinstance(grad_req, str):
            self.grad_req = {}
            for name in self.arg_names:
                if name in self.param_names and name not in self.fixed_param_names:
                    self.grad_req[name] = grad_req if for_training else "null"
                elif name in self.data_names:
                    self.grad_req[name] = grad_req if inputs_need_grad else "null"
                else:
                    self.grad_req[name] = "null"
        else:
            self.grad_req = dict(grad_req)

        # low-precision lane (reference fp16 flow, `docs/faq/perf.md:161-178`;
        # on TPU the type is bfloat16): when every data input is declared
        # bf16/fp16 via DataDesc.dtype, parameters are bound in that dtype so
        # the matmuls/convs hit the MXU natively.  Aux states (BatchNorm
        # running stats) and labels keep their own dtypes — stats accumulate
        # in fp32, and the multi-precision optimizer keeps fp32 masters.
        type_dict = None
        data_dtypes = {_np.dtype(d.dtype) for d in self.data_shapes}
        if len(data_dtypes) == 1 and \
                next(iter(data_dtypes)).name in ("float16", "bfloat16"):
            low = next(iter(data_dtypes))
            label_names_set = set(self.label_names)
            type_dict = {n: low for n in self.arg_names
                         if n not in label_names_set}
            for l in self.label_shapes:
                type_dict[l.name] = _np.dtype(l.dtype)

        self.execs = []
        for i, ctx in enumerate(contexts):
            shard = self.slices[i]
            shapes = {}
            for d in self.data_shapes:
                shapes[d.name] = (shard.stop - shard.start,) + d.shape[1:]
            for l in self.label_shapes:
                shapes[l.name] = (shard.stop - shard.start,) + l.shape[1:]
            self.execs.append(symbol.simple_bind(ctx=ctx,
                                                 grad_req=self.grad_req,
                                                 type_dict=type_dict,
                                                 **shapes))

        # param/grad arrays grouped across devices: [n_params][n_devices]
        self.param_arrays = [[e.arg_dict[name] for e in self.execs]
                             for name in self.param_names]
        self.grad_arrays = [[e.grad_dict.get(name) for e in self.execs]
                            for name in self.param_names]
        self.aux_arrays = [[e.aux_dict[name] for e in self.execs]
                           for name in self.aux_names]

    def set_params(self, arg_params, aux_params, allow_extra=False):
        for e in self.execs:
            e.copy_params_from(arg_params, aux_params,
                               allow_extra_params=allow_extra)

    def get_params(self, arg_params, aux_params):
        """Average params over devices into the given dicts (reference
        `executor_group.py get_params`).

        The device->host movement happens as ONE batched fetch: a round
        trip per parameter at every epoch boundary dominates wall clock on
        a remote chip.  When all device copies alias the same buffer (the
        fused train step repoints every executor at one global array) the
        average is skipped outright."""
        import jax

        names, merged = [], []
        for name, block in zip(list(self.param_names) + list(self.aux_names),
                               list(self.param_arrays) + list(self.aux_arrays)):
            if len(block) == 1 or all(b._data is block[0]._data
                                      for b in block[1:]):
                val = block[0]._data
            else:
                dev = block[0].context.jax_device
                acc = block[0]._data
                for b in block[1:]:
                    acc = acc + jax.device_put(b._data, dev)
                val = acc / len(block)
            names.append(name)
            merged.append(val)
        host = jax.device_get(merged)
        for name, h in zip(names, host):
            tgt_dict = arg_params if name in self.param_names else aux_params
            if name in tgt_dict:
                tgt = tgt_dict[name]
                tgt._set_data(jax.device_put(
                    h.astype(tgt.dtype, copy=False) if h.dtype != tgt.dtype
                    else h, tgt.context.jax_device))
            else:
                tgt_dict[name] = nd.array(h, dtype=h.dtype)

    def _slice_batch(self, arrays, names):
        """Slice each input along batch dim per device shard."""
        out = []
        for i, _ in enumerate(self.execs):
            shard = self.slices[i]
            dev_inputs = {}
            for name, arr in zip(names, arrays):
                dev_inputs[name] = arr[shard.start:shard.stop] \
                    if (shard.start, shard.stop) != (0, arr.shape[0]) else arr
            out.append(dev_inputs)
        return out

    def forward(self, data_batch, is_train=None):
        if is_train is None:
            is_train = self.for_training
        data = data_batch.data
        label = data_batch.label or []
        per_dev = self._slice_batch(list(data) + list(label),
                                    self.data_names + self.label_names)
        for e, inputs in zip(self.execs, per_dev):
            e.forward(is_train=is_train, **inputs)

    def forward_backward(self, data_batch):
        """Fused per-device train step (single XLA program per device)."""
        data = data_batch.data
        label = data_batch.label or []
        per_dev = self._slice_batch(list(data) + list(label),
                                    self.data_names + self.label_names)
        for e, inputs in zip(self.execs, per_dev):
            e.forward_backward(**inputs)

    def backward(self, out_grads=None):
        for e in self.execs:
            e.backward(out_grads)

    def get_outputs(self, merge_multi_context=True):
        if not merge_multi_context:
            return [[e.outputs[i] for e in self.execs]
                    for i in range(len(self.execs[0].outputs))]
        merged = []
        for i in range(len(self.execs[0].outputs)):
            parts = [e.outputs[i] for e in self.execs]
            if len(parts) == 1:
                merged.append(parts[0])
            else:
                merged.append(nd.concatenate([p.copyto(parts[0].context)
                                              for p in parts], axis=0))
        return merged

    def get_input_grads(self, merge_multi_context=True):
        grads = []
        for name in self.data_names:
            parts = [e.grad_dict.get(name) for e in self.execs]
            if merge_multi_context and len(parts) > 1:
                grads.append(nd.concatenate(parts, axis=0))
            else:
                grads.append(parts[0] if len(parts) == 1 else parts)
        return grads

    def update_metric(self, eval_metric, labels):
        for ei, e in enumerate(self.execs):
            shard = self.slices[ei]
            labels_slice = [l[shard.start:shard.stop]
                            if (shard.start, shard.stop) != (0, l.shape[0])
                            else l for l in labels]
            eval_metric.update(labels_slice, e.outputs)
