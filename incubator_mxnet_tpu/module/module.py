"""Module: symbolic training on one or more devices
(reference `python/mxnet/module/module.py` — bind:364, forward:573,
backward:627, update:644)."""
from __future__ import annotations

import logging

from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..initializer import Uniform, InitDesc
from .. import optimizer as opt
from ..model import (_create_kvstore, _initialize_kvstore, _update_params,
                     _update_params_on_kvstore, load_checkpoint,
                     save_checkpoint)
from ..ndarray.ndarray import NDArray
from .. import ndarray as nd
from ..obs import trace as _obs_trace
from .base_module import BaseModule, _as_list
from .executor_group import DataParallelExecutorGroup


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger)
        if context is None:
            context = cpu()
        if isinstance(context, Context):
            context = [context]
        self._context = context
        self._work_load_list = work_load_list or [1] * len(context)

        self._symbol = symbol
        data_names = list(data_names) if data_names is not None else []
        label_names = list(label_names) if label_names is not None else []
        arg_names = symbol.list_arguments()
        input_names = data_names + label_names
        self._param_names = [x for x in arg_names if x not in input_names]
        self._fixed_param_names = list(fixed_param_names or [])
        self._aux_names = symbol.list_auxiliary_states()
        self._data_names = data_names
        self._label_names = label_names
        self._state_names = list(state_names or [])
        self._output_names = symbol.list_outputs()

        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False
        self._compression_params = compression_params
        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._preload_opt_states = None
        self._exec_group = None
        self._data_shapes = None
        self._label_shapes = None
        self._fused_step = None
        self._mesh = None

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """Reference `module.py load`."""
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        """Reference `module.py save_checkpoint`."""
        self._sync_params_from_devices()
        save_checkpoint(prefix, epoch, self.symbol, self._arg_params,
                        self._aux_params)
        if save_optimizer_states:
            state_name = "%s-%04d.states" % (prefix, epoch)
            self.save_optimizer_states(state_name)

    # -- properties ------------------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        outs = self._exec_group.execs[0].outputs
        return list(zip(self._output_names, [o.shape for o in outs]))

    # -- params ----------------------------------------------------------------
    def get_params(self):
        assert self.binded and self.params_initialized
        self._sync_params_from_devices()
        return (self._arg_params, self._aux_params)

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before initializing the parameters"
        self._flush_fused()
        if initializer is None:
            initializer = Uniform(0.01)

        from .. import engine as _engine
        # bulk scope: parameter drafts and initializer writes host-stage and
        # flush as batched transfers — per-array device round trips dominate
        # init on a remote chip otherwise (reference analogue: deferred
        # alloc + engine bulk, `include/mxnet/engine.h:308`)
        with _engine.bulk(1 << 16):
            if self._arg_params is None:
                self._arg_params = {
                    name: nd.zeros(
                        self._exec_group.execs[0].arg_dict[name].shape,
                        dtype=self._exec_group.execs[0].arg_dict[name].dtype)
                    for name in self._param_names}
            if self._aux_params is None:
                self._aux_params = {
                    name: nd.zeros(
                        self._exec_group.execs[0].aux_dict[name].shape,
                        dtype=self._exec_group.execs[0].aux_dict[name].dtype)
                    for name in self._aux_names}

        def _impl(desc, arr, cache):
            # desc carries the variable's attr dict (__init__ etc.) — the
            # initializer dispatches on it, so it must not be rebuilt bare
            if cache is not None:
                if str(desc) in cache:
                    cache_arr = cache[str(desc)]
                    if cache_arr is not arr:
                        cache_arr.copyto(arr)
                else:
                    if not allow_missing:
                        raise RuntimeError(f"{desc} is not presented")
                    if initializer is not None:
                        initializer(desc, arr)
            else:
                if initializer is not None:
                    initializer(desc, arr)

        attrs = self._symbol.attr_dict()
        with _engine.bulk(1 << 16):
            for name, arr in sorted(self._arg_params.items()):
                desc = InitDesc(name, attrs.get(name, None))
                _impl(desc, arr, arg_params)
            for name, arr in sorted(self._aux_params.items()):
                desc = InitDesc(name, attrs.get(name, None))
                _impl(desc, arr, aux_params)

        self.params_initialized = True
        self._params_dirty = False
        self._exec_group.set_params(self._arg_params, self._aux_params,
                                    allow_extra=allow_extra)

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self._flush_fused()
        if not allow_missing:
            self.init_params(initializer=None, arg_params=arg_params,
                             aux_params=aux_params, allow_missing=allow_missing,
                             force_init=force_init, allow_extra=allow_extra)
            return
        if self.params_initialized and not force_init:
            return
        self._exec_group.set_params(arg_params, aux_params,
                                    allow_extra=allow_extra)
        self._params_dirty = True
        self.params_initialized = True

    # -- bind ------------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True

        self._data_shapes = data_shapes
        self._label_shapes = label_shapes

        self._exec_group = DataParallelExecutorGroup(
            self._symbol, self._context, self._work_load_list, data_shapes,
            label_shapes, self._param_names, for_training, inputs_need_grad,
            fixed_param_names=self._fixed_param_names, grad_req=grad_req,
            state_names=self._state_names)
        if self.params_initialized:
            self._exec_group.set_params(self._arg_params, self._aux_params)

    def _reset_bind(self):
        self.binded = False
        self._exec_group = None
        self._data_shapes = None
        self._label_shapes = None
        self._fused_step = None

    def reshape(self, data_shapes, label_shapes=None):
        assert self.binded
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes
        self.binded = False
        self.bind(data_shapes, label_shapes, self.for_training,
                  self.inputs_need_grad, force_rebind=True)
        if self.params_initialized:
            self._exec_group.set_params(self._arg_params, self._aux_params)

    # -- optimizer -------------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False, mesh=None):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring...")
            return
        if self._params_dirty:
            self._sync_params_from_devices()

        # composed device mesh for the fused step: a jax Mesh, a spec
        # string/dict ('dp=4,tp=2'), or None (MXNET_MESH env spec, else
        # the default 1-D dp mesh over the contexts)
        if mesh is not None and not hasattr(mesh, "axis_names"):
            from ..parallel.mesh import mesh_from_spec
            mesh = mesh_from_spec(
                mesh, devices=[c.jax_device for c in self._context])
        self._mesh = mesh

        (kvstore, update_on_kvstore) = _create_kvstore(
            kvstore, len(self._context), self._arg_params)
        batch_size = self._exec_group.batch_size
        if kvstore and "dist" in kvstore.type and "_sync" in kvstore.type:
            # sync distributed training averages over the global batch
            # (reference module.py:504)
            batch_size *= kvstore.num_workers
        rescale_grad = 1.0 / batch_size

        # TPU fast path eligibility must be decided BEFORE the kvstore /
        # updater wiring: when the fused step will own the optimizer, the
        # kvstore must never get an optimizer installed (a later unfused
        # update() would then apply it to its own weight copies and pull
        # weights back as gradients) and idx2name must use the per-device
        # layout the local updater / fused indices share
        fusable = self._fusable(kvstore)
        if fusable:
            update_on_kvstore = False

        idx2name = {}
        if update_on_kvstore:
            idx2name.update(enumerate(self._exec_group.param_names))
        else:
            for k in range(len(self._context)):
                idx2name.update({i * len(self._context) + k: n
                                 for i, n in
                                 enumerate(self._exec_group.param_names)})
        if isinstance(optimizer, str):
            optimizer_params = dict(optimizer_params)
            if "rescale_grad" not in optimizer_params:
                optimizer_params["rescale_grad"] = rescale_grad
            optimizer = opt.create(optimizer, sym=self.symbol,
                                   param_idx2name=idx2name, **optimizer_params)
        else:
            assert isinstance(optimizer, opt.Optimizer)
            if optimizer.rescale_grad != rescale_grad:
                self.logger.warning(
                    "Optimizer created manually outside Module but rescale_grad "
                    f"is not normalized to 1.0/batch_size/num_workers "
                    f"({optimizer.rescale_grad} vs. {rescale_grad}). Is this "
                    "intended?")

        self._optimizer = optimizer
        self._kvstore = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._updater = None

        if kvstore:
            if self._compression_params:
                kvstore.set_gradient_compression(self._compression_params)
            _initialize_kvstore(kvstore=kvstore,
                                param_arrays=self._exec_group.param_arrays,
                                arg_params=self._arg_params,
                                param_names=self._param_names,
                                update_on_kvstore=update_on_kvstore)
        if update_on_kvstore:
            kvstore.set_optimizer(self._optimizer)
        else:
            self._updater = opt.get_updater(optimizer)

        # TPU fast path: compile forward+backward+optimizer+metric into ONE
        # donated XLA program per signature (fused.FusedTrainStep) — the
        # public equivalent of the reference's bulk-exec segments + fused
        # update ops (`graph_executor.cc:1194-1316`, `optimizer_op.cc`).
        # Optimizer state lives in self._updater either way, so the
        # fused path and the unfused fallback share one state store.
        self._fused_step = None
        if fusable:
            try:
                from .. import fused as _fused
                self._fused_step = _fused.FusedTrainStep(self, self._updater)
            except Exception as e:  # never block training on the fast path
                self.logger.warning(
                    "fused train step unavailable (%s); Module.fit uses "
                    "forward_backward+update", str(e)[:200])
                self._fused_step = None

        self.optimizer_initialized = True
        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    def _fusable(self, kvstore):
        """Whether fit can run the single-program fused train step."""
        from .. import config as _config
        if not _config.get("MXNET_FUSED_TRAIN_STEP"):
            return False
        if self._state_names or self.inputs_need_grad or not self.for_training:
            return False
        if self._compression_params:
            return False
        if any(v not in ("write", "null")
               for v in self._exec_group.grad_req.values()):
            return False
        if kvstore is not None and \
                getattr(kvstore, "type", "") not in ("local", "device", "tpu"):
            return False
        ndev = len(self._context)
        if ndev > 1:
            if len({c.device_type for c in self._context}) > 1:
                return False
            bs = self._exec_group.batch_size
            if bs % ndev or any(
                    (s.stop - s.start) != bs // ndev
                    for s in self._exec_group.slices):
                return False
            # loss heads with batch/valid normalization divide the gradient
            # by the batch they SEE: the fused single program sees the
            # global batch, the unfused per-device path normalizes by the
            # device slice and sums — a factor-ndev difference.  Keep such
            # graphs on the unfused (reference-semantics) path.
            for n in self._symbol._topo():
                if not n.is_variable and \
                        n.attrs.get("normalization") in ("batch", "valid"):
                    return False
        return True

    def fit_step(self, data_batch, eval_metric):
        """One train step + metric update; fused single-program when
        available (see init_optimizer), reference semantics otherwise.
        Traced as one span — the kvstore push/pull rpc spans it issues
        parent into it, so a training step reads as one connected tree
        across worker and server processes in the merged trace."""
        with _obs_trace.span("fit.step", cat="train"):
            if self._fused_step is not None and \
                    self._fused_step(data_batch, eval_metric):
                return
            self.forward_backward(data_batch)
            self.update()
            self.update_metric(eval_metric, data_batch.label)

    def _fit_block_k(self):
        """K batches per `fit` dispatch: when the fused step is live, one
        `lax.scan` program runs K steps per dispatch (the reference's
        bulk-exec-segment idea, `graph_executor.cc:1194-1316`, taken to
        its XLA-native conclusion)."""
        fs = self._fused_step
        if fs is None or fs.broken:
            return 1
        from .. import config as _config
        return max(int(_config.get("MXNET_FUSED_STEP_BLOCK")), 1)

    def fit_block(self, data_batches, eval_metric):
        """Run a block of batches as ONE fused scan dispatch.  On False the
        fit loop runs the block per-batch (fused 1-step or unfused); the
        pre-dispatch eligibility checks are cheap, so blocks keep being
        attempted — a later block may fuse (e.g. after deferred state
        materializes)."""
        fs = self._fused_step
        if fs is None:
            return False
        with _obs_trace.span("fit.step_block", cat="train",
                             k=len(data_batches)) as sp:
            ran = fs.call_block(data_batches, eval_metric)
            sp.note(fused=bool(ran))
        return ran

    def _fit_block_cursor(self, j):
        """Point get_outputs() AND the in-graph metric totals at batch j
        of the last block while the fit loop fires that batch's
        callbacks (per-logical-step callback semantics for K>1)."""
        fs = self._fused_step
        if fs is not None:
            fs.set_block_cursor(j)

    # -- forward/backward ------------------------------------------------------
    def prepare(self, data_batch, sparse_row_id_fn=None):
        """Pre-stage the upcoming batch's device transfer while the
        current step computes (reference `PrefetcherIter`'s H2D role)."""
        super().prepare(data_batch, sparse_row_id_fn=sparse_row_id_fn)
        fs = self._fused_step
        if fs is not None and not fs.broken and fs._carry is not None:
            # only while the fused path is ACTIVE (a step has run and the
            # carry is armed): otherwise the eager path would transfer the
            # batch a second time
            fs.prestage(data_batch)

    def _flush_fused(self):
        """Deferred fused-step write-backs must land before anything reads
        the public param/state/aux NDArrays (see fused.FusedTrainStep.flush)."""
        if self._fused_step is not None:
            self._fused_step.flush()

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        if self._fused_step is not None:
            self._fused_step.clear_outputs()
            self._fused_step.flush()
        self._exec_group.forward(data_batch, is_train)

    def forward_backward(self, data_batch):
        """Fused train step (one XLA program per device)."""
        assert self.binded and self.params_initialized
        self._flush_fused()
        self._exec_group.forward_backward(data_batch)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec_group.backward(out_grads=out_grads)

    def update(self):
        """Apply optimizer using accumulated gradients
        (reference `module.py:644 update`)."""
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        self._flush_fused()
        self._params_dirty = True
        if self._update_on_kvstore:
            _update_params_on_kvstore(self._exec_group.param_arrays,
                                      self._exec_group.grad_arrays,
                                      self._kvstore,
                                      self._exec_group.param_names)
        else:
            if self._fused_step is not None and len(self._context) > 1:
                self._seed_fallback_states()
            _update_params(self._exec_group.param_arrays,
                           self._exec_group.grad_arrays,
                           updater=self._updater,
                           num_device=len(self._context),
                           kvstore=self._kvstore,
                           param_names=self._exec_group.param_names)

    def _seed_fallback_states(self):
        """The fused step keeps optimizer state under device-0 indices
        (i*ndev) only; the unfused per-device update uses i*ndev+k.  A
        mid-training fallback batch must not start devices k>=1 from
        freshly zeroed state — seed them with copies of the fused state so
        the per-device weight copies stay in lockstep."""
        from ..ndarray.ndarray import NDArray

        def _copy_state(s):
            if s is None:
                return None
            if isinstance(s, NDArray):
                return s.copy()
            if isinstance(s, (tuple, list)):
                return tuple(_copy_state(x) for x in s)
            return s

        ndev = len(self._context)
        upd = self._updater
        for i in range(len(self._exec_group.param_names)):
            base = i * ndev
            if base not in upd.states:
                continue
            for k in range(1, ndev):
                if base + k not in upd.states:
                    upd.states[base + k] = _copy_state(upd.states[base])
                    upd.states_synced[base + k] = True

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        if self._fused_step is not None:
            outs = self._fused_step.current_outputs()
            if outs is not None:
                # last step ran fused: outputs are the global-batch arrays
                # (in block mode, the view follows the callback cursor so a
                # batch-j callback reads batch j's outputs)
                return outs
        return self._exec_group.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and self.inputs_need_grad
        return self._exec_group.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        self._exec_group.update_metric(eval_metric, labels)

    def _sync_params_from_devices(self):
        if self._exec_group is None or not self._params_dirty:
            return
        self._flush_fused()
        if self._arg_params is None:
            self._arg_params = {}
        if self._aux_params is None:
            self._aux_params = {}
        self._exec_group.get_params(self._arg_params, self._aux_params)
        if self._kvstore and self._update_on_kvstore:
            for param_name, param_val in sorted(self._arg_params.items()):
                self._kvstore.pull(param_name, param_val)
        self._params_dirty = False

    def get_optimizer_states_blob(self):
        """Full optimizer state as one bytes blob (the checkpoint plane's
        capture point): local updater slots + the pickled optimizer
        (num_update / LR-scheduler position travel along); with a
        server-side optimizer (`update_on_kvstore` on a dist store) the
        slots are pulled back through the kvstore control channel."""
        assert self.optimizer_initialized
        self._flush_fused()
        if self._update_on_kvstore:
            getter = getattr(self._kvstore, "get_optimizer_states", None)
            if getter is None:
                raise MXNetError(
                    f"kvstore {self._kvstore.type!r} runs the optimizer "
                    "server-side but cannot export its state")
            return getter(dump_optimizer=True)
        return self._updater.get_states(dump_optimizer=True)

    def set_optimizer_states_blob(self, blob):
        assert self.optimizer_initialized
        self._flush_fused()  # stale pending state must not clobber the load
        if self._update_on_kvstore:
            setter = getattr(self._kvstore, "set_optimizer_states", None)
            if setter is None:
                raise MXNetError(
                    f"kvstore {self._kvstore.type!r} runs the optimizer "
                    "server-side but cannot restore its state")
            setter(blob)
            return
        self._updater.set_states(blob)
        # a resumed optimizer must keep counting updates where it left off:
        # when the blob carried the pickled optimizer, adopt it as THE
        # optimizer so Module and Updater agree on num_update
        restored = getattr(self._updater, "optimizer", None)
        if isinstance(restored, opt.Optimizer):
            self._optimizer = restored
            if self._fused_step is not None:
                # the fused program captured the PRE-restore optimizer at
                # construction (FusedTrainStep.__init__ caches
                # updater.optimizer); rebuild it or every fused step would
                # keep advancing the stale instance from num_update=0
                # while the restored one stays frozen
                try:
                    from .. import fused as _fused
                    self._fused_step = _fused.FusedTrainStep(self,
                                                             self._updater)
                except Exception as e:
                    self.logger.warning(
                        "fused train step unavailable after optimizer "
                        "state restore (%s); falling back to "
                        "forward_backward+update", str(e)[:200])
                    self._fused_step = None

    def save_optimizer_states(self, fname):
        with open(fname, "wb") as fout:
            fout.write(self.get_optimizer_states_blob())

    def load_optimizer_states(self, fname):
        with open(fname, "rb") as fin:
            self.set_optimizer_states_blob(fin.read())

    def install_monitor(self, mon):
        assert self.binded
        for exe in self._exec_group.execs:
            mon.install(exe)
