"""RecordIO: packed binary record files (reference `python/mxnet/recordio.py`,
dmlc-core recordio format).

Byte-compatible with the reference format so existing `.rec` datasets work:
records are [magic uint32 0xced7230a][lrecord uint32][data][pad to 4B],
where lrecord encodes cflag (3 bits) | length (29 bits).  `IRHeader`
(flag, label, id, id2) matches `mx.recordio.IRHeader` for image records.

Corruption tolerance (training-guardian io tier): a truncated/torn tail
record, a magic mismatch, or a broken multi-part sequence used to raise
`MXNetError` mid-epoch.  The reader now SKIPS the damaged region — it
resynchronizes on the next magic word where possible, otherwise treats
the tail as EOF — emits one structured warning per event (capped), and
counts every skip on ``corrupt_records``; a quarantine log attached via
`set_quarantine` receives one entry per skip (source + byte offset), so
a resumed run can avoid the region entirely.  The
``io.corrupt_record`` fault site (`resilience.faults.mutate`) fires on
every successfully read record, so chaos schedules can bit-flip payloads
deterministically without hand-built fixture files.
"""
from __future__ import annotations

import ctypes
import logging
import os
import struct
import numbers

import numpy as np

from .base import MXNetError

_log = logging.getLogger(__name__)
_WARN_CAP = 5   # per-reader structured warnings before dropping to debug

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img", "shard_range", "shard_ranges"]


def shard_range(n, num_parts, part_index):
    """THE per-host input-partition rule: contiguous ``[start, stop)``
    over `n` records for shard `part_index` of `num_parts`, with the
    remainder spread over the first shards.  Deterministic, disjoint
    and exhaustive — every record belongs to exactly one shard, and the
    same ``(n, num_parts, part_index)`` always yields the same range
    (the resume/re-shard invariant the epoch fence relies on).  Shared
    by `ImageRecordIter`/`ImageIter` auto-sharding and the data-plane
    tests."""
    n = int(n)
    num_parts = int(num_parts)
    part_index = int(part_index)
    if num_parts < 1 or not 0 <= part_index < num_parts:
        raise MXNetError(
            f"shard_range: part_index {part_index} out of range for "
            f"num_parts {num_parts}")
    per, rem = divmod(n, num_parts)
    start = part_index * per + min(part_index, rem)
    return start, start + per + (1 if part_index < rem else 0)


def shard_ranges(n, num_parts):
    """Every shard's ``(start, stop)`` under `shard_range`'s rule."""
    return [shard_range(n, num_parts, p) for p in range(int(num_parts))]

_MAGIC = 0xced7230a
_CFLAG_BITS = 29


class MXRecordIO:
    """Sequential reader/writer (reference `recordio.py:MXRecordIO`)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self.corrupt_records = 0
        self._quarantine = None
        self.open()

    def open(self):
        if self.flag == "w":
            self.handle = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.handle = open(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)
        self.is_open = True
        self.corrupt_records = 0

    def set_quarantine(self, log):
        """Attach a `resilience.guardian.QuarantineLog`: every corrupt
        region this reader skips appends one entry (source + offset)."""
        self._quarantine = log

    def _corrupt(self, reason, offset=None):
        """Count + report one skipped corrupt region (never raises)."""
        self.corrupt_records += 1
        where = self.uri if offset is None else f"{self.uri}@{offset}"
        if self.corrupt_records <= _WARN_CAP:
            _log.warning("RecordIO: skipping corrupt record in %s: %s "
                         "(corrupt_records=%d)", where, reason,
                         self.corrupt_records)
        else:
            _log.debug("RecordIO: skipping corrupt record in %s: %s",
                       where, reason)
        if self._quarantine is not None:
            try:
                self._quarantine.append(reason="corrupt_record",
                                        source=self.uri,
                                        offset=offset, detail=reason)
            except Exception:
                pass
        try:
            from .resilience import faults as _faults
            _faults.note("corrupt-record", site="io.corrupt_record",
                         uri=self.uri, detail=str(reason)[:200])
        except Exception:
            pass

    def _resync(self):
        """Scan forward for the next magic word; position the handle at
        it and report success.  The skipped bytes are one counted
        corrupt region; no magic until EOF means the tail is garbage."""
        magic = struct.pack("<I", _MAGIC)
        window = b""
        while True:
            chunk = self.handle.read(1 << 16)
            if not chunk:
                return False
            window += chunk
            hit = window.find(magic)
            if hit != -1:
                # rewind to the magic word (handle sits past the window)
                self.handle.seek(hit - len(window), os.SEEK_CUR)
                return True
            window = window[-3:]   # a magic may straddle the boundary

    def close(self):
        if self.is_open:
            self.handle.close()
            self.is_open = False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __getstate__(self):
        d = dict(self.__dict__)
        d["handle"] = None
        if d["is_open"]:
            d["is_open"] = False
            d["_reopen"] = True
        return d

    def __setstate__(self, d):
        reopen = d.pop("_reopen", False)
        self.__dict__.update(d)
        if reopen:
            self.open()

    def reset(self):
        self.close()
        self.open()

    def _write_part(self, cflag, buf):
        length = len(buf)
        lrecord = (cflag << _CFLAG_BITS) | length
        self.handle.write(struct.pack("<II", _MAGIC, lrecord))
        self.handle.write(buf)
        pad = (4 - length % 4) % 4
        if pad:
            self.handle.write(b"\x00" * pad)

    def write(self, buf):
        """Write one logical record, splitting at in-payload magic words so
        any dmlc-compatible scanner stays synchronized (cflag 1/2/3
        multi-part encoding; the magic bytes at each split are implied by
        the next part's header and not stored)."""
        assert self.writable
        buf = bytes(buf)
        magic = struct.pack("<I", _MAGIC)
        if magic not in buf:
            self._write_part(0, buf)
            return
        parts = buf.split(magic)
        for i, part in enumerate(parts):
            cflag = 1 if i == 0 else (3 if i == len(parts) - 1 else 2)
            self._write_part(cflag, part)

    def _read_part(self):
        while True:
            offset = self.handle.tell()
            header = self.handle.read(8)
            if not header:
                return None, None           # clean EOF
            if len(header) < 8:
                # torn tail: a writer died mid-header
                self._corrupt("short header (%d of 8 bytes)"
                              % len(header), offset)
                return None, None
            magic, lrecord = struct.unpack("<II", header)
            if magic != _MAGIC:
                # bit-flip / foreign bytes: resynchronize on the next
                # magic word (one counted skip); no magic -> EOF
                self._corrupt("magic mismatch (0x%08x)" % magic, offset)
                self.handle.seek(offset + 1)
                if not self._resync():
                    return None, None
                continue
            cflag = lrecord >> _CFLAG_BITS
            length = lrecord & ((1 << _CFLAG_BITS) - 1)
            buf = self.handle.read(length)
            if len(buf) < length:
                # torn tail: payload cut short by a dying writer
                self._corrupt("short payload (%d of %d bytes)"
                              % (len(buf), length), offset)
                return None, None
            pad = (4 - length % 4) % 4
            if pad:
                self.handle.read(pad)
            return cflag, buf

    def read(self):
        """Read one logical record, reassembling multi-part sequences.

        dmlc-core writers split any payload containing the magic word into
        parts (cflag 1=start, 2=middle, 3=end), dropping the 4 magic bytes
        at each split point; readers re-insert the magic between parts
        (dmlc-core recordio semantics mirrored by reference
        `src/io/` iterators).

        Corrupt structure never raises: damaged regions are skipped and
        counted on ``corrupt_records`` (see the module docstring), and
        the assembled record passes through the ``io.corrupt_record``
        fault site so chaos schedules can damage payloads in flight.
        """
        assert not self.writable
        while True:
            cflag, buf = self._read_part()
            if cflag is None:
                return None
            if cflag == 0:
                return self._deliver(buf)
            if cflag != 1:
                # a continuation part at record start: the reader lost
                # the sequence head (corrupt region) — skip forward
                self._corrupt("unexpected continuation flag %d at "
                              "record start" % cflag)
                continue
            parts = [buf]
            while True:
                cflag, buf = self._read_part()
                if cflag is None:
                    self._corrupt("truncated multi-part record at EOF")
                    return None
                if cflag == 2:
                    parts.append(buf)
                    continue
                if cflag == 3:
                    parts.append(buf)
                    return self._deliver(
                        struct.pack("<I", _MAGIC).join(parts))
                # a fresh record START interrupted the sequence: the
                # previous record is torn — drop it, adopt this part
                self._corrupt("multi-part record interrupted by flag %d"
                              % cflag)
                if cflag == 0:
                    return self._deliver(buf)
                parts = [buf]

    def _deliver(self, rec):
        """Route one assembled record through the ``io.corrupt_record``
        payload fault site (one global read without a configured
        schedule — `faults.mutate`'s own fast path)."""
        from .resilience import faults as _faults
        return _faults.mutate("io.corrupt_record", rec, uri=self.uri)

    def tell(self):
        return self.handle.tell()

    def seek(self, pos):
        assert not self.writable
        self.handle.seek(pos)


class MXIndexedRecordIO(MXRecordIO):
    """Random-access reader/writer with .idx file
    (reference `recordio.py:MXIndexedRecordIO`)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if self.flag == "w":
            self.fidx = open(self.idx_path, "w")
        else:
            self.fidx = open(self.idx_path, "r")
            for line in self.fidx:
                parts = line.strip().split("\t")
                key = self.key_type(parts[0])
                self.idx[key] = int(parts[1])
                self.keys.append(key)

    def close(self):
        if self.is_open:
            super().close()
            self.fidx.close()

    def read_idx(self, idx):
        """Record `idx`'s payload, or None when the region at its index
        offset is damaged.  `read()`'s magic-mismatch resync must NOT
        leak here: resyncing forward salvages the NEXT record, and
        returning it as `idx`'s would silently train a misaligned
        sample/label pair — worse than the corruption itself.  The
        damaged id feeds the quarantine log so resume drops it."""
        self.seek(self.idx[idx])
        before = self.corrupt_records
        rec = self.read()
        if self.corrupt_records != before:
            if self._quarantine is not None:
                try:
                    self._quarantine.append(reason="corrupt_record",
                                            source=self.uri,
                                            record=int(idx)
                                            if isinstance(idx, int)
                                            else None)
                except Exception:
                    pass
            return None
        return rec

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write(f"{key}\t{pos}\n")
        self.idx[key] = pos
        self.keys.append(key)


class IRHeader:
    """Image record header (reference `recordio.py:IRHeader` namedtuple)."""

    __slots__ = ("flag", "label", "id", "id2")

    def __init__(self, flag, label, id, id2):  # noqa: A002
        self.flag = flag
        self.label = label
        self.id = id
        self.id2 = id2

    def __iter__(self):
        yield from (self.flag, self.label, self.id, self.id2)

    def __eq__(self, other):
        return tuple(self) == tuple(other)


_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack header + bytes (reference `recordio.py pack`)."""
    flag, label, id_, id2 = header
    if isinstance(label, numbers.Number):
        hdr = struct.pack(_IR_FORMAT, 0, float(label), id_, id2)
        return hdr + s
    label = np.asarray(label, dtype=np.float32)
    hdr = struct.pack(_IR_FORMAT, label.size, 0.0, id_, id2)
    return hdr + label.tobytes() + s


def unpack(s):
    """Unpack to (IRHeader, bytes) (reference `recordio.py unpack`)."""
    flag, label, id_, id2 = struct.unpack(_IR_FORMAT, s[:_IR_SIZE])
    payload = s[_IR_SIZE:]
    if flag > 0:
        label = np.frombuffer(payload[:flag * 4], dtype=np.float32)
        payload = payload[flag * 4:]
    return IRHeader(flag, label, id_, id2), payload


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Encode image + pack (reference `recordio.py pack_img`; PIL instead of
    OpenCV — documented divergence, same bytes-on-disk container)."""
    import io as _io
    from PIL import Image
    if isinstance(img, np.ndarray):
        img = Image.fromarray(img.astype(np.uint8))
    buf = _io.BytesIO()
    fmt = "JPEG" if img_fmt.lower() in (".jpg", ".jpeg") else "PNG"
    img.save(buf, format=fmt, quality=quality)
    return pack(header, buf.getvalue())


def unpack_img(s, iscolor=-1):
    """Unpack + decode image to numpy HWC (reference `recordio.py unpack_img`)."""
    import io as _io
    from PIL import Image
    header, payload = unpack(s)
    img = Image.open(_io.BytesIO(payload))
    if iscolor == 0:
        img = img.convert("L")
    elif iscolor == 1:
        img = img.convert("RGB")
    return header, np.asarray(img)
