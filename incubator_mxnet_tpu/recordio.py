"""RecordIO: packed binary record files (reference `python/mxnet/recordio.py`,
dmlc-core recordio format).

Byte-compatible with the reference format so existing `.rec` datasets work:
records are [magic uint32 0xced7230a][lrecord uint32][data][pad to 4B],
where lrecord encodes cflag (3 bits) | length (29 bits).  `IRHeader`
(flag, label, id, id2) matches `mx.recordio.IRHeader` for image records.
"""
from __future__ import annotations

import ctypes
import os
import struct
import numbers

import numpy as np

from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_MAGIC = 0xced7230a
_CFLAG_BITS = 29


class MXRecordIO:
    """Sequential reader/writer (reference `recordio.py:MXRecordIO`)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self.open()

    def open(self):
        if self.flag == "w":
            self.handle = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.handle = open(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)
        self.is_open = True

    def close(self):
        if self.is_open:
            self.handle.close()
            self.is_open = False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __getstate__(self):
        d = dict(self.__dict__)
        d["handle"] = None
        if d["is_open"]:
            d["is_open"] = False
            d["_reopen"] = True
        return d

    def __setstate__(self, d):
        reopen = d.pop("_reopen", False)
        self.__dict__.update(d)
        if reopen:
            self.open()

    def reset(self):
        self.close()
        self.open()

    def _write_part(self, cflag, buf):
        length = len(buf)
        lrecord = (cflag << _CFLAG_BITS) | length
        self.handle.write(struct.pack("<II", _MAGIC, lrecord))
        self.handle.write(buf)
        pad = (4 - length % 4) % 4
        if pad:
            self.handle.write(b"\x00" * pad)

    def write(self, buf):
        """Write one logical record, splitting at in-payload magic words so
        any dmlc-compatible scanner stays synchronized (cflag 1/2/3
        multi-part encoding; the magic bytes at each split are implied by
        the next part's header and not stored)."""
        assert self.writable
        buf = bytes(buf)
        magic = struct.pack("<I", _MAGIC)
        if magic not in buf:
            self._write_part(0, buf)
            return
        parts = buf.split(magic)
        for i, part in enumerate(parts):
            cflag = 1 if i == 0 else (3 if i == len(parts) - 1 else 2)
            self._write_part(cflag, part)

    def _read_part(self):
        header = self.handle.read(8)
        if len(header) < 8:
            return None, None
        magic, lrecord = struct.unpack("<II", header)
        if magic != _MAGIC:
            raise MXNetError("Invalid RecordIO magic")
        cflag = lrecord >> _CFLAG_BITS
        length = lrecord & ((1 << _CFLAG_BITS) - 1)
        buf = self.handle.read(length)
        pad = (4 - length % 4) % 4
        if pad:
            self.handle.read(pad)
        return cflag, buf

    def read(self):
        """Read one logical record, reassembling multi-part sequences.

        dmlc-core writers split any payload containing the magic word into
        parts (cflag 1=start, 2=middle, 3=end), dropping the 4 magic bytes
        at each split point; readers re-insert the magic between parts
        (dmlc-core recordio semantics mirrored by reference
        `src/io/` iterators).
        """
        assert not self.writable
        cflag, buf = self._read_part()
        if cflag is None:
            return None
        if cflag == 0:
            return buf
        if cflag != 1:
            raise MXNetError(
                f"RecordIO: unexpected continuation flag {cflag} at record "
                "start (corrupt file or reader desynchronized)")
        parts = [buf]
        while True:
            cflag, buf = self._read_part()
            if cflag is None:
                raise MXNetError("RecordIO: truncated multi-part record")
            if cflag not in (2, 3):
                raise MXNetError(
                    f"RecordIO: invalid flag {cflag} inside multi-part record")
            parts.append(buf)
            if cflag == 3:
                break
        return struct.pack("<I", _MAGIC).join(parts)

    def tell(self):
        return self.handle.tell()

    def seek(self, pos):
        assert not self.writable
        self.handle.seek(pos)


class MXIndexedRecordIO(MXRecordIO):
    """Random-access reader/writer with .idx file
    (reference `recordio.py:MXIndexedRecordIO`)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if self.flag == "w":
            self.fidx = open(self.idx_path, "w")
        else:
            self.fidx = open(self.idx_path, "r")
            for line in self.fidx:
                parts = line.strip().split("\t")
                key = self.key_type(parts[0])
                self.idx[key] = int(parts[1])
                self.keys.append(key)

    def close(self):
        if self.is_open:
            super().close()
            self.fidx.close()

    def read_idx(self, idx):
        self.seek(self.idx[idx])
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write(f"{key}\t{pos}\n")
        self.idx[key] = pos
        self.keys.append(key)


class IRHeader:
    """Image record header (reference `recordio.py:IRHeader` namedtuple)."""

    __slots__ = ("flag", "label", "id", "id2")

    def __init__(self, flag, label, id, id2):  # noqa: A002
        self.flag = flag
        self.label = label
        self.id = id
        self.id2 = id2

    def __iter__(self):
        yield from (self.flag, self.label, self.id, self.id2)

    def __eq__(self, other):
        return tuple(self) == tuple(other)


_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack header + bytes (reference `recordio.py pack`)."""
    flag, label, id_, id2 = header
    if isinstance(label, numbers.Number):
        hdr = struct.pack(_IR_FORMAT, 0, float(label), id_, id2)
        return hdr + s
    label = np.asarray(label, dtype=np.float32)
    hdr = struct.pack(_IR_FORMAT, label.size, 0.0, id_, id2)
    return hdr + label.tobytes() + s


def unpack(s):
    """Unpack to (IRHeader, bytes) (reference `recordio.py unpack`)."""
    flag, label, id_, id2 = struct.unpack(_IR_FORMAT, s[:_IR_SIZE])
    payload = s[_IR_SIZE:]
    if flag > 0:
        label = np.frombuffer(payload[:flag * 4], dtype=np.float32)
        payload = payload[flag * 4:]
    return IRHeader(flag, label, id_, id2), payload


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Encode image + pack (reference `recordio.py pack_img`; PIL instead of
    OpenCV — documented divergence, same bytes-on-disk container)."""
    import io as _io
    from PIL import Image
    if isinstance(img, np.ndarray):
        img = Image.fromarray(img.astype(np.uint8))
    buf = _io.BytesIO()
    fmt = "JPEG" if img_fmt.lower() in (".jpg", ".jpeg") else "PNG"
    img.save(buf, format=fmt, quality=quality)
    return pack(header, buf.getvalue())


def unpack_img(s, iscolor=-1):
    """Unpack + decode image to numpy HWC (reference `recordio.py unpack_img`)."""
    import io as _io
    from PIL import Image
    header, payload = unpack(s)
    img = Image.open(_io.BytesIO(payload))
    if iscolor == 0:
        img = img.convert("L")
    elif iscolor == 1:
        img = img.convert("RGB")
    return header, np.asarray(img)
