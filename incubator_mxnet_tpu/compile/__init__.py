"""Unified program cache with a persistent on-disk tier and AOT warmup.

This package replaces the three independent per-signature program
caches the framework grew — `fused.FusedTrainStep`'s train programs,
`fused.FusedInference` (serving / c_predict), and Gluon's CachedOp
graphs (`gluon/block.py`) — with ONE cache product:

* **memory tier** — `CachedProgram` (program.py): a jit-shaped wrapper,
  one compiled executable per input signature, centrally registered so
  signatures/compiles/hit-rates are observable in one place;
* **disk tier** — `ProgramCache` (cache.py): XLA serialized
  executables keyed by graph-hash x shapes x dtypes x donation x
  device/mesh fingerprint, CRC'd and atomically published, versioned
  eviction; a second process loads instead of compiling;
* **AOT warmup** — warmup.py: manifest-driven
  ``jax.jit(...).lower().compile()`` so serving ladders and resumed
  training jobs pay compilation before traffic, or never (disk hit);
* **stats plane** — `stats()` / `findings()` feed
  `analysis.runtime_report()` and the ``mxlint --cache-report`` CLI;
  compiles are attributable to churned signatures via the recompile
  auditor's history.

Knobs: ``MXNET_PROGRAM_CACHE`` (master switch),
``MXNET_PROGRAM_CACHE_DIR`` (disk tier location),
``MXNET_PROGRAM_CACHE_LIMIT_MB`` (LRU size cap),
``MXNET_PROGRAM_CACHE_CHECKPOINT`` (ship programs/ with elastic
checkpoints).
"""
from __future__ import annotations

import atexit
import os

from ..analysis import locks as _alocks

from .cache import ProgramCache, device_fingerprint, entry_key  # noqa: F401
from .program import (CachedProgram, cached_jit,  # noqa: F401
                      graph_hash_of_jaxpr, graph_hash_of_text)
from . import warmup  # noqa: F401
from .warmup import warm, write_manifest, export_all  # noqa: F401

__all__ = ["ProgramCache", "CachedProgram", "cached_jit", "get_cache",
           "set_cache_dir", "add_source", "enabled", "stats",
           "write_stats", "findings", "warm", "write_manifest",
           "export_all", "graph_hash_of_jaxpr", "graph_hash_of_text",
           "device_fingerprint", "entry_key"]

_cache = None
_cache_lock = _alocks.make_lock("compile.registry")
_enabled = None   # tri-state: None = read MXNET_PROGRAM_CACHE lazily
_atexit_armed = False


def enabled():
    """Master switch (MXNET_PROGRAM_CACHE): off -> every wrapper is a
    plain jax.jit, the pre-unification behavior."""
    global _enabled
    if _enabled is None:
        from .. import config as _config
        _enabled = bool(_config.get("MXNET_PROGRAM_CACHE"))
    return _enabled


def get_cache():
    """The process-wide ProgramCache (disk tier configured from
    MXNET_PROGRAM_CACHE_DIR on first use)."""
    global _cache
    if _cache is None:
        with _cache_lock:
            if _cache is None:
                from .. import config as _config
                c = ProgramCache()
                d = str(_config.get("MXNET_PROGRAM_CACHE_DIR") or "")
                if d:
                    c.set_directory(d)
                    _arm_atexit(c)
                _cache = c
    return _cache


def _arm_atexit(cache):
    """Persist the stats sidecar at exit when a disk tier exists (the
    mxlint cache-report aggregates these across runs).  The handler
    resolves the CURRENT singleton at exit time, so re-pointing the
    cache (tests, embedding processes) flushes the right directory."""
    del cache
    global _atexit_armed
    if _atexit_armed:
        return
    _atexit_armed = True

    def _flush():
        c = _cache
        if c is not None and c.directory is not None:
            try:
                c.write_stats()
            except Exception:
                pass
    atexit.register(_flush)


def set_cache_dir(path):
    """Point (or re-point) the disk tier at `path`; also the test/tool
    entry point (MXNET_PROGRAM_CACHE_DIR is the env equivalent)."""
    c = get_cache()
    c.set_directory(path)
    if c.directory:
        _arm_atexit(c)
    return c


def add_source(path):
    """Register a read-only entry payload (checkpoint programs/ dir)."""
    get_cache().add_source(path)


def stats():
    return get_cache().stats()


def write_stats(path=None):
    return get_cache().write_stats(path)


def reset_for_tests():
    """Drop the singleton (tests that flip env knobs between cases).
    The atexit flush reads the live singleton, so a replacement cache
    created after this still gets its stats written."""
    global _cache, _enabled
    with _cache_lock:
        _cache = None
    _enabled = None


def findings():
    """Program-cache findings for `analysis.runtime_report()`: a summary
    HINT plus a WARN per program whose repeat compiles line up with
    signatures the recompile auditor flagged as churn."""
    from ..analysis.findings import Finding, WARN, HINT
    from ..analysis import recompile as _recompile
    cache = _cache
    if cache is None:
        return []
    st = cache.stats()
    c = st["counters"]
    out = []
    lookups = c["compiles"] + c["mem_hits"] + c["disk_hits"]
    if lookups:
        out.append(Finding(
            "cache.programs", "summary", HINT,
            "program cache: %d compiles, %d disk hits, %d memory hits "
            "(hit rate %.1f%%), %d stored, %d corrupt, %d evicted"
            % (c["compiles"], c["disk_hits"], c["mem_hits"],
               100.0 * (c["mem_hits"] + c["disk_hits"]) / lookups,
               c["stores"], c["corrupt"], c["evicted"]),
            location=st["directory"] or "<memory>"))
    # attribute repeat compiles to churn only when the recompile auditor
    # actually flagged the program (pre-registered warmup buckets are
    # declared signatures, not churn)
    churn_keys = {f.location for f in _recompile.findings()}
    for p in st["programs"]:
        if p["compiles"] > 1 and p["label"] in churn_keys:
            out.append(Finding(
                "cache.programs", "churn-compiles", WARN,
                "%s: %d XLA compiles across %d signatures — each extra "
                "signature paid a full compile; see the recompile "
                "auditor's shape-churn findings for the argument that "
                "moved" % (p["label"], p["compiles"], p["signatures"]),
                location=p["label"]))
    return out
