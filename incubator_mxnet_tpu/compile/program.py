"""CachedProgram: the jit-shaped front of the unified program cache.

Every compiled-program site in the framework — the fused train steps
(`fused.FusedTrainStep`, `gluon.fused_step.GluonFusedStep`), the
inference cache (`fused.FusedInference`), Gluon's CachedOp graphs —
used to keep its own private per-signature jit cache.  They now share
this wrapper: one `CachedProgram` per logical graph, holding one
compiled executable per input signature, with the signatures, compile
counts and disk-tier traffic visible on the central `ProgramCache`.

Call path per signature:

1. memory tier — the executable this wrapper already holds;
2. disk tier  — `ProgramCache.load` (a serialized executable written by
   an earlier process/warmup/checkpoint payload), when a graph key and
   a cache location exist;
3. compile    — ``jit.lower(*args).compile()`` (the AOT build the
   warmup API also drives), then best-effort serialize to the disk
   tier for the next process.

AOT executables validate their inputs strictly (exact dtypes/shardings,
no weak-type promotion).  A signature whose dispatch trips that
validation permanently falls back to the plain ``jax.jit`` path for
this wrapper — never an error on the caller, and donation is checked
before any replay so a consumed buffer is never dispatched twice.

``MXNET_PROGRAM_CACHE=0`` disables the whole layer: every wrapper
degrades to its plain jit (the pre-unification behavior).
"""
from __future__ import annotations

import hashlib
import logging
import re
import time as _time

from ..analysis import locks as _alocks

__all__ = ["CachedProgram", "cached_jit", "graph_hash_of_jaxpr",
           "graph_hash_of_text"]

_log = logging.getLogger(__name__)

_ADDR_RE = re.compile(r"0x[0-9a-f]+")


def graph_hash_of_text(*parts):
    """Stable hash over textual graph identities (symbol JSON, op names,
    parameter partitions...)."""
    h = hashlib.sha256()
    for p in parts:
        h.update(repr(p).encode())
        h.update(b"\x00")
    return h.hexdigest()[:32]


def graph_hash_of_jaxpr(closed_jaxpr):
    """Stable cross-process hash of a traced core: the jaxpr
    pretty-print with memory addresses scrubbed (function reprs inside
    eqn params would otherwise churn the key every process), PLUS the
    closure constants' VALUES — the print shows consts only as typed
    constvars, so two cores baking different lookup tables would
    otherwise hash identically and a disk hit would silently replay the
    other table."""
    h = hashlib.sha256()
    h.update(_ADDR_RE.sub("0x", str(closed_jaxpr)).encode())
    import numpy as _np
    for c in getattr(closed_jaxpr, "consts", ()):
        try:
            a = _np.asarray(c)
            h.update(repr((str(a.dtype), a.shape)).encode())
            h.update(a.tobytes())
        except Exception:
            h.update(repr(c).encode())
    return h.hexdigest()[:32]


def _leaf_sig(leaf):
    shape = getattr(leaf, "shape", None)
    if shape is not None:
        return (tuple(shape), str(leaf.dtype))
    # weak-typed python scalar: distinct from a committed 0-d array
    return ("py", type(leaf).__name__)


_PLAIN = object()   # sentinel: this signature dispatches via plain jit


class CachedProgram:
    """One logical program; one executable per input signature."""

    def __init__(self, fn, donate_argnums=(), graph_key=None, label="",
                 cache=None):
        import jax
        self._fn = fn
        self._donate = tuple(donate_argnums or ())
        self._jit = jax.jit(fn, donate_argnums=self._donate) \
            if self._donate else jax.jit(fn)
        self.graph_key = graph_key
        self.label = label or (graph_key[:12] if graph_key else "program")
        self._programs = {}     # sig -> executable | _PLAIN
        self._entry_keys = {}   # sig -> disk entry key (for export)
        self._lock = _alocks.make_lock("compile.program")
        self.compile_count = 0
        self.disk_hits = 0
        self.disk_misses = 0   # disk tier enabled but had no entry
        self.mem_hits = 0   # plain int: the warm path must not take locks
        self.lower_s_total = 0.0    # trace->StableHLO seconds (cold only)
        self.compile_s_total = 0.0  # XLA compile seconds (cold only)
        if cache is None:
            from . import get_cache
            cache = get_cache()
        self._cache = cache
        cache.register_program(self)

    # -- signature -----------------------------------------------------------
    def _sig(self, args):
        import jax
        leaves, treedef = jax.tree_util.tree_flatten(args)
        return (treedef, tuple(_leaf_sig(l) for l in leaves))

    def signatures(self):
        with self._lock:
            return list(self._programs)

    def _cache_size(self):
        """Signature count — the drop-in for ``jax.jit._cache_size()``
        that `FusedInference.program_count` (and the serving zero-
        recompile certification) reads."""
        return len(self._programs)

    # -- acquire -------------------------------------------------------------
    def _entry_key(self, sig):
        from . import cache as _cache
        sig_repr = (str(sig[0]), sig[1])
        return _cache.entry_key(self.graph_key, sig_repr, self._donate)

    def _acquire(self, sig, args):
        from . import enabled as _enabled
        cache = self._cache
        if not _enabled():
            return _PLAIN
        key = None
        if self.graph_key is not None:
            key = self._entry_key(sig)
            # live tier first: an in-process restart (fit failover,
            # guardian rollback, supervisor shrink-and-resume) rebuilds
            # its wrappers around executables this process ALREADY holds
            # — reuse them directly.  Deserializing a disk clone of a
            # still-live executable is never correct here: wasted work,
            # and the clone's coexistence with the original corrupts
            # runtime state on teardown (see ProgramCache._live).
            exe = cache.live_get(key)
            if exe is not None:
                self._entry_keys[sig] = key
                return exe
            if cache.enabled():
                exe = cache.load(key)
                if exe is not None:
                    self.disk_hits += 1
                    self._entry_keys[sig] = key
                    cache.live_put(key, exe)
                    return exe
                self.disk_misses += 1
                cache.bump("disk_misses")
        sig_repr = "%d leaves: %s" % (len(sig[1]), repr(sig[1])[:160])
        self.compile_count += 1
        # phase-split timing: lower (trace -> StableHLO) vs the XLA
        # compile proper — the cold-start debt mxtop's CACHE line and
        # bench's compile_phases block report per program
        t0 = _time.perf_counter()
        lowered = self._jit.lower(*args)
        t1 = _time.perf_counter()
        exe = lowered.compile()
        t2 = _time.perf_counter()
        self.lower_s_total += t1 - t0
        self.compile_s_total += t2 - t1
        cache.note_compile(self.label, sig_repr, lower_s=t1 - t0,
                           compile_s=t2 - t1)
        if key is not None:
            cache.live_put(key, exe)
            if cache.enabled() and \
                    cache.store(key, exe, meta={"label": self.label,
                                                "graph": self.graph_key,
                                                "donate": list(self._donate)}):
                self._entry_keys[sig] = key
        return exe

    # -- dispatch ------------------------------------------------------------
    def __call__(self, *args):
        sig = self._sig(args)
        exe = self._programs.get(sig)
        warm = exe is not None
        if not warm:
            with self._lock:
                exe = self._programs.get(sig)
                warm = exe is not None
                if not warm:
                    try:
                        exe = self._acquire(sig, args)
                    except Exception:
                        # a failed lower/compile never consumed buffers;
                        # surface through the plain path so the caller's
                        # existing triage (fused fallbacks) sees the
                        # same exception surface as before unification
                        exe = _PLAIN
                    self._programs[sig] = exe
        if warm:
            # per-program plain increment: the steady-state dispatch path
            # takes no lock (GIL-racy across threads costs at most a few
            # stat counts, never correctness); stats() aggregates
            self.mem_hits += 1
        if exe is _PLAIN:
            return self._jit(*args)
        try:
            return exe(*args)
        except TypeError as e:
            # AOT input validation is stricter than jit (weak types,
            # shardings).  Validation raises BEFORE execution, so the
            # args are intact — but donation makes replay destructive,
            # so verify nothing was consumed before re-dispatching.
            from ..analysis import donation as _donation
            if self._donate and _donation.any_deleted(args):
                raise
            _log.warning("program %s: AOT dispatch rejected the inputs "
                         "(%s); pinning this signature to the plain jit "
                         "path", self.label, str(e)[:200])
            with self._lock:
                self._programs[sig] = _PLAIN
            self._cache.bump("fallbacks")
            return self._jit(*args)

    # -- export (checkpoint programs/ payload, warmed images) ---------------
    def export_to(self, directory):
        """Serialize every AOT-held executable into `directory` as
        standard cache entries (skipping ones already on disk there).
        Returns the number of entries written."""
        from . import cache as _cache
        import os
        wrote = 0
        with self._lock:
            items = list(self._programs.items())
        if self.graph_key is None:
            return 0
        target = os.path.join(str(directory), "v%d" % _cache.FORMAT_VERSION)
        for sig, exe in items:
            if exe is _PLAIN or exe is None:
                continue
            key = self._entry_keys.get(sig) or self._entry_key(sig)
            path = os.path.join(target, key + ".xprog")
            if os.path.exists(path) and \
                    key not in self._cache.corrupt_keys:
                # a key the loader flagged corrupt (torn payload copy we
                # could not delete in a read-only source) is REWRITTEN:
                # skipping it would leave every future resume paying the
                # full compile while exports report the payload shipped
                continue
            header = {"label": self.label, "graph": self.graph_key,
                      "donate": list(self._donate),
                      "format": _cache.FORMAT_VERSION,
                      "fingerprint": _cache.device_fingerprint()}
            try:
                blob = self._cache.serialize_entry(exe, header)
                self._cache.write_entry(target, key, blob, overwrite=True)
                self._cache.corrupt_keys.discard(key)
                wrote += 1
            except Exception as e:
                _log.debug("program export skipped for %s (%s)",
                           self.label, str(e)[:200])
        return wrote


def cached_jit(fn, donate_argnums=(), graph_key=None, label="",
               cache=None):
    """`jax.jit`-shaped constructor for a `CachedProgram`."""
    return CachedProgram(fn, donate_argnums=donate_argnums,
                         graph_key=graph_key, label=label, cache=cache)
