"""AOT warmup: compile a declared program set ahead of traffic.

A warmup *manifest* is a JSON document naming the programs a process
will need — served models with their shape-bucket ladders, plus any
pre-exported entry payload directories:

    {
      "version": 1,
      "models": [
        {"name": "resnet", "symbol": "resnet-symbol.json",
         "params": "resnet-0000.params",        # optional: shapes suffice
         "data_shapes": [["data", [1, 3, 224, 224]]],
         "buckets": [1, 2, 4, 8, 16, 32], "dtype": "float32"}
      ],
      "programs": ["programs"]                  # entry dirs (relative ok)
    }

``warm(manifest)`` drives `jax.jit(...).lower().compile()` for every
bucket of every model through the unified program cache: with a disk
tier configured the compiles are persisted, so the NEXT process —
`ServedModel` warmup, `c_predict`, `Module.fit(resume=)` — loads
executables instead of compiling.  Parameters are optional because the
compiled program depends only on shapes/dtypes: zeros of the inferred
parameter shapes produce the identical executable the production
weights will hit.

`warm` is what `tools/warmup.py` wraps; `selftest` is the tiny built-in
model both the parity runner's cold-start stage and bench.py use to
measure cold-vs-warm compile time.
"""
from __future__ import annotations

import json
import logging
import os
import time

import numpy as _np

__all__ = ["warm", "write_manifest", "selftest", "export_all"]

_log = logging.getLogger(__name__)

MANIFEST_VERSION = 1


def write_manifest(path, models, programs=()):
    """Write a warmup manifest; `models` entries follow the schema in
    the module docstring (shapes as lists, paths relative to the
    manifest's directory where possible)."""
    doc = {"version": MANIFEST_VERSION, "models": list(models),
           "programs": list(programs)}
    tmp = str(path) + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, path)
    return path


def _resolve(base, p):
    if p is None:
        return None
    return p if os.path.isabs(p) else os.path.join(base, p)


def _zero_params(symbol, input_shapes, dtype):
    """Zeros for every non-input argument/aux at the shapes inference
    implies — a warmup needs the program, not the weights (same shapes
    => same executable)."""
    arg_shapes, _, aux_shapes = symbol.infer_shape(**input_shapes)
    args, auxs = {}, {}
    for n, s in zip(symbol.list_arguments(), arg_shapes or []):
        if n not in input_shapes and s is not None:
            args[n] = _np.zeros(s, _np.dtype(dtype))
    for n, s in zip(symbol.list_auxiliary_states(), aux_shapes or []):
        if s is not None:
            auxs[n] = _np.zeros(s, _np.float32)
    return args, auxs


def warm(manifest, cache_dir=None):
    """Run the AOT warmup a manifest describes.  `manifest` is a path
    or an already-parsed dict.  Returns a summary dict (per-model
    compile/disk-hit counts and wall time) suitable for JSON output."""
    from . import get_cache, set_cache_dir
    from .. import symbol as _sym
    from ..serving.model import ServedModel

    base = "."
    if not isinstance(manifest, dict):
        base = os.path.dirname(os.path.abspath(manifest))
        with open(manifest) as f:
            manifest = json.load(f)
    if cache_dir:
        set_cache_dir(cache_dir)
    cache = get_cache()
    for pdir in manifest.get("programs", ()):
        cache.add_source(_resolve(base, pdir))

    summary = {"models": [], "compiles": 0, "disk_hits": 0}
    t0 = time.perf_counter()
    for spec in manifest.get("models", ()):
        name = spec.get("name", "model")
        sym = _sym.load(_resolve(base, spec["symbol"]))
        data_shapes = [(n, tuple(s)) for n, s in spec["data_shapes"]]
        dtype = spec.get("dtype", "float32")
        params_file = _resolve(base, spec.get("params"))
        if params_file:
            from .. import nd as _nd
            args, auxs = {}, {}
            for k, v in _nd.load(params_file).items():
                tp, _, pname = k.partition(":")
                (args if tp == "arg" else auxs)[pname or k] = v
        else:
            args, auxs = _zero_params(
                sym, {n: s for n, s in data_shapes}, dtype)
        model = ServedModel(
            sym, args, auxs, data_shapes=data_shapes,
            buckets=tuple(spec.get("buckets", (1,))), name=name,
            dtype=dtype)
        before = dict(cache.counters)
        t_model = time.perf_counter()
        model.warmup()
        summary["models"].append({
            "name": name,
            "buckets": list(model.buckets),
            "compile_s": round(time.perf_counter() - t_model, 3),
            "compiles": cache.counters["compiles"] - before["compiles"],
            "disk_hits": cache.counters["disk_hits"] -
            before["disk_hits"],
        })
    summary["compiles"] = sum(m["compiles"] for m in summary["models"])
    summary["disk_hits"] = sum(m["disk_hits"] for m in summary["models"])
    summary["compile_s"] = round(time.perf_counter() - t0, 3)
    cache.write_stats()
    return summary


def export_all(directory):
    """Serialize every live cached program into `directory` as entry
    files (the checkpoint ``programs/`` payload writer)."""
    from . import get_cache
    wrote = 0
    for p in get_cache().programs():
        wrote += p.export_to(directory)
    return wrote


def _selftest_symbol():
    """A small MLP — big enough that XLA compile time is measurable,
    small enough for a sub-second warmup when the disk tier hits."""
    from .. import sym
    x = sym.Variable("data")
    h = sym.FullyConnected(x, num_hidden=256, name="fc1")
    h = sym.Activation(h, act_type="relu")
    h = sym.FullyConnected(h, num_hidden=128, name="fc2")
    h = sym.Activation(h, act_type="tanh")
    out = sym.FullyConnected(h, num_hidden=10, name="fc3")
    return sym.SoftmaxOutput(out, name="softmax")


def selftest(cache_dir, buckets=(1, 4)):
    """Warm a built-in model against `cache_dir` and report what it
    cost — run once cold and once (in a fresh process) warm, the two
    numbers are the cold-start story for this machine."""
    from . import get_cache, set_cache_dir
    set_cache_dir(cache_dir)
    manifest = {
        "version": MANIFEST_VERSION,
        "models": [{
            "name": "selftest-mlp",
            "symbol": None,   # built below, not loaded
            "data_shapes": [["data", [1, 64]]],
            "buckets": list(buckets),
        }],
    }
    # inline model: bypass the file round trip warm() normally does
    from ..serving.model import ServedModel
    symbol = _selftest_symbol()
    args, auxs = _zero_params(symbol, {"data": (1, 64)}, "float32")
    cache = get_cache()
    before = dict(cache.counters)
    t0 = time.perf_counter()
    model = ServedModel(symbol, args, auxs,
                        data_shapes=[("data", (1, 64))],
                        buckets=tuple(buckets), name="selftest-mlp")
    model.warmup()
    out = {
        "compile_s": round(time.perf_counter() - t0, 3),
        "compiles": cache.counters["compiles"] - before["compiles"],
        "disk_hits": cache.counters["disk_hits"] - before["disk_hits"],
        "buckets": list(buckets),
        "audit_key": model.audit_key,
        "manifest": manifest,
    }
    cache.write_stats()
    return out
