"""The persistent tier of the unified program cache.

One `ProgramCache` per process owns a writable cache *directory* (the
disk tier) plus any number of read-only *sources* (e.g. a checkpoint's
``programs/`` payload).  Entries are XLA serialized executables — the
output of ``jax.jit(...).lower().compile()`` run through
`jax.experimental.serialize_executable` — keyed by

    graph-hash x input signature (shapes/dtypes/pytree) x donation spec
    x device/mesh fingerprint x jax version x format version

so a second process that builds the same program loads the compiled
executable from disk instead of paying the multi-second XLA compile
(BENCH_r03–r05: 28–105 s per cold start on the fused train graphs).

Entry files are corruption-safe by construction:

* written to a temp name and published with one atomic ``os.replace`` —
  a concurrent writer of the same key loses the race harmlessly (both
  wrote identical bytes) and a crash mid-write leaves only a temp file;
* framed as ``MAGIC | header-length | header-JSON | payload | CRC32``;
  a torn or bit-flipped entry fails the CRC (or the header parse) on
  load, is deleted, and the caller falls back to a fresh compile;
* self-describing: the header repeats the key ingredients, so an entry
  produced under a different jax version / backend / format is evicted
  instead of deserialized (versioned eviction).

The disk tier activates when a directory is configured
(``MXNET_PROGRAM_CACHE_DIR`` or `set_cache_dir`); without one the
unified cache still runs its memory tier (see program.py) and can
export entries on demand (checkpoint ``programs/`` payloads).
"""
from __future__ import annotations

import binascii
import json
import logging
import os
import pickle
import struct
import tempfile

from ..analysis import locks as _alocks

__all__ = ["ProgramCache", "device_fingerprint", "entry_key",
           "FORMAT_VERSION"]

_log = logging.getLogger(__name__)

FORMAT_VERSION = 1
_MAGIC = b"MXPROG01"
_SUFFIX = ".xprog"


def device_fingerprint():
    """Fingerprint of the device topology an executable is specialized
    to: platform, device kind, local/global device and process counts,
    the jax version, and the framework version (an op-implementation
    change across releases must not serve a stale executable through a
    symbol-JSON-keyed entry).  Serialized executables are only valid on
    an identical topology (the compiled program bakes in the mesh)."""
    import jax
    from ..libinfo import __version__ as _fw_version
    devs = jax.devices()
    return "|".join([
        jax.default_backend(),
        getattr(devs[0], "device_kind", "?"),
        "d%d" % len(devs),
        "p%d" % jax.process_count(),
        "jax=" + jax.__version__,
        "fw=" + _fw_version,
    ])


def entry_key(graph_key, signature, donation, fingerprint=None):
    """Content hash naming one cache entry file.

    `graph_key` is the caller's stable graph identity (symbol-JSON hash,
    sanitized jaxpr hash, ...), `signature` the abstract input signature
    (pytree structure + per-leaf shape/dtype), `donation` the
    donate_argnums spec.  A false hit on any ingredient would replay the
    wrong program, so ALL of them feed the hash."""
    import hashlib
    if fingerprint is None:
        fingerprint = device_fingerprint()
    blob = repr((FORMAT_VERSION, fingerprint, graph_key, signature,
                 tuple(donation or ()))).encode()
    return hashlib.sha256(blob).hexdigest()[:48]


def _frame(header, payload):
    head = json.dumps(header, sort_keys=True).encode()
    body = _MAGIC + struct.pack("<I", len(head)) + head + payload
    return body + struct.pack("<I", binascii.crc32(body) & 0xFFFFFFFF)


def _unframe(blob):
    """(header, payload) of a framed entry, or None when torn/corrupt."""
    if len(blob) < len(_MAGIC) + 8 or not blob.startswith(_MAGIC):
        return None
    body, (crc,) = blob[:-4], struct.unpack("<I", blob[-4:])
    if binascii.crc32(body) & 0xFFFFFFFF != crc:
        return None
    (hlen,) = struct.unpack("<I", body[len(_MAGIC):len(_MAGIC) + 4])
    hstart = len(_MAGIC) + 4
    if hstart + hlen > len(body):
        return None
    try:
        header = json.loads(body[hstart:hstart + hlen].decode())
    except ValueError:
        return None
    return header, body[hstart + hlen:]


class ProgramCache:
    """Disk tier + stats plane of the unified program cache.

    Thread-safe; all methods are best-effort — a cache failure degrades
    to a recompile, never to an error on the caller's path."""

    def __init__(self, directory=None, sources=(), limit_mb=None):
        self._lock = _alocks.make_lock("compile.cache")
        self.directory = None
        self.sources = []
        self._limit_mb = limit_mb
        self.counters = {"compiles": 0, "mem_hits": 0, "disk_hits": 0,
                         "disk_misses": 0, "live_hits": 0, "stores": 0,
                         "corrupt": 0, "evicted": 0, "errors": 0,
                         "fallbacks": 0, "lower_s_total": 0.0,
                         "compile_s_total": 0.0}
        self.events = []       # per-compile: {label, signature} (capped)
        self._programs = []    # weakrefs of live CachedPrograms
        # live tier: entry-key -> the loaded executable THIS process
        # already holds.  An in-process restart (fit failover, guardian
        # rollback, supervisor shrink-and-resume) rebuilds its fused
        # steps; without this tier the rebuilt wrapper would deserialize
        # a CLONE of an executable that is still alive in this process —
        # wasted work, and with the original alive the clone's teardown
        # double-frees runtime state on this jaxlib (observed glibc heap
        # corruption).  Bounded LRU; entries are dropped oldest-first.
        self._live = {}
        self._live_cap = 64
        # keys whose entry was found corrupt/stale in a READ-ONLY source
        # (we cannot delete there): the next export of that key rewrites
        # instead of skipping the existing bad file
        self.corrupt_keys = set()
        # telemetry plane: hit/compile/eviction counters under the
        # stable 'cache' namespace (weakly held; newest cache answers)
        from ..obs import metrics as _obs_metrics
        _obs_metrics.register_producer("cache", self.stats)
        if directory:
            self.set_directory(directory)
        for s in sources:
            self.add_source(s)

    # -- configuration -------------------------------------------------------
    def _version_dir(self, root):
        return os.path.join(str(root), "v%d" % FORMAT_VERSION)

    def set_directory(self, directory):
        """Point the writable disk tier at `directory` (created on
        demand; entries live under a format-versioned subdirectory so a
        format bump orphans — and `prune` deletes — old entries)."""
        if not directory:
            self.directory = None
            return
        path = self._version_dir(directory)
        try:
            os.makedirs(path, exist_ok=True)
        except OSError as e:
            _log.warning("program cache dir %r unusable (%s); disk tier "
                         "disabled", directory, e)
            self.directory = None
            return
        self.directory = path

    def add_source(self, directory):
        """Register a read-only entry location (a checkpoint's
        ``programs/`` payload, a warmed cache shipped with a container
        image).  Missing directories are accepted silently — payloads
        are optional by design."""
        if not directory:
            return
        for root in (self._version_dir(directory), str(directory)):
            if os.path.isdir(root) and root not in self.sources \
                    and root != self.directory:
                self.sources.append(root)
                return

    @property
    def limit_mb(self):
        if self._limit_mb is not None:
            return self._limit_mb
        from .. import config as _config
        return int(_config.get("MXNET_PROGRAM_CACHE_LIMIT_MB"))

    def enabled(self):
        return self.directory is not None or bool(self.sources)

    # -- live tier (in-process executables) ----------------------------------
    def live_get(self, key):
        """The already-loaded executable for `key`, if this process holds
        one (compiled or deserialized earlier) — the in-process restart
        fast path: no compile, no deserialize."""
        with self._lock:
            exe = self._live.get(key)
            if exe is not None:
                self.counters["live_hits"] += 1
                # LRU touch
                self._live[key] = self._live.pop(key)
            return exe

    def live_put(self, key, exe):
        with self._lock:
            self._live[key] = exe
            while len(self._live) > self._live_cap:
                self._live.pop(next(iter(self._live)))

    # -- lookup / store ------------------------------------------------------
    def _paths(self, key):
        fname = key + _SUFFIX
        if self.directory is not None:
            yield os.path.join(self.directory, fname)
        for src in self.sources:
            yield os.path.join(src, fname)

    def load(self, key, expect_fingerprint=None):
        """Deserialize the entry for `key` -> loaded executable, or None.

        Corrupt entries are deleted (primary dir only); entries whose
        header disagrees with the current format/jax/device fingerprint
        are evicted rather than deserialized."""
        from jax.experimental import serialize_executable as _se
        fp = expect_fingerprint or device_fingerprint()
        for path in self._paths(key):
            try:
                with open(path, "rb") as f:
                    blob = f.read()
            except OSError:
                continue
            got = _unframe(blob)
            if got is None:
                with self._lock:
                    self.counters["corrupt"] += 1
                    self.corrupt_keys.add(key)
                self._discard(path)
                continue
            header, payload = got
            if header.get("format") != FORMAT_VERSION or \
                    header.get("fingerprint") != fp:
                with self._lock:
                    self.counters["evicted"] += 1
                    self.corrupt_keys.add(key)
                self._discard(path)
                continue
            try:
                ser, in_tree, out_tree = pickle.loads(payload)
                exe = _se.deserialize_and_load(ser, in_tree, out_tree)
            except Exception as e:
                _log.warning("program cache entry %s failed to "
                             "deserialize (%s); recompiling", path,
                             str(e)[:200])
                with self._lock:
                    self.counters["corrupt"] += 1
                    self.corrupt_keys.add(key)
                self._discard(path)
                continue
            try:  # LRU currency for the size-cap eviction
                os.utime(path, None)
            except OSError:
                pass
            with self._lock:
                self.counters["disk_hits"] += 1
            return exe
        return None

    def _discard(self, path):
        """Remove a bad/stale entry — only where we own the file."""
        if self.directory and path.startswith(self.directory):
            try:
                os.unlink(path)
            except OSError:
                pass

    def serialize_entry(self, compiled, header):
        """Frame one executable as entry bytes (shared by `store` and
        the checkpoint/export path, which writes into a payload dir)."""
        import pickle as _pickle
        from jax.experimental import serialize_executable as _se
        ser, in_tree, out_tree = _se.serialize(compiled)
        return _frame(header, _pickle.dumps((ser, in_tree, out_tree)))

    def write_entry(self, directory, key, blob, overwrite=False):
        """Atomically publish framed entry bytes under `directory`."""
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, key + _SUFFIX)
        if os.path.exists(path) and not overwrite:
            return path
        fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)   # atomic: readers see whole entries only
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return path

    def store(self, key, compiled, meta=None):
        """Serialize + publish one compiled executable into the primary
        directory.  Returns the entry path, or None (disk tier off, or
        the backend cannot serialize this executable)."""
        if self.directory is None:
            return None
        header = dict(meta or {})
        header.update(format=FORMAT_VERSION,
                      fingerprint=device_fingerprint())
        try:
            blob = self.serialize_entry(compiled, header)
            path = self.write_entry(self.directory, key, blob)
        except Exception as e:
            with self._lock:
                self.counters["errors"] += 1
            _log.warning("program cache store failed for %s (%s)",
                         meta.get("label", key) if meta else key,
                         str(e)[:200])
            return None
        with self._lock:
            self.counters["stores"] += 1
        self._enforce_limit()
        return path

    # -- maintenance ---------------------------------------------------------
    def _entries(self):
        if self.directory is None:
            return []
        out = []
        try:
            for name in os.listdir(self.directory):
                if name.endswith(_SUFFIX):
                    path = os.path.join(self.directory, name)
                    try:
                        st = os.stat(path)
                        out.append((st.st_mtime, st.st_size, path))
                    except OSError:
                        pass
        except OSError:
            pass
        return out

    def _enforce_limit(self):
        """LRU size cap: drop the stalest entries past the MB budget."""
        limit = self.limit_mb * (1 << 20)
        entries = sorted(self._entries())
        total = sum(sz for _, sz, _ in entries)
        for mtime, sz, path in entries:
            if total <= limit:
                break
            self._discard(path)
            total -= sz
            with self._lock:
                self.counters["evicted"] += 1

    def prune(self):
        """Delete orphaned old-format version dirs + corrupt entries."""
        removed = 0
        if self.directory is None:
            return removed
        root = os.path.dirname(self.directory)
        import shutil
        try:
            for name in os.listdir(root):
                path = os.path.join(root, name)
                if name.startswith("v") and os.path.isdir(path) \
                        and path != self.directory:
                    shutil.rmtree(path, ignore_errors=True)
                    removed += 1
        except OSError:
            pass
        for _, _, path in self._entries():
            try:
                with open(path, "rb") as f:
                    if _unframe(f.read()) is None:
                        self._discard(path)
                        removed += 1
            except OSError:
                pass
        return removed

    # -- stats plane ---------------------------------------------------------
    def note_compile(self, label, sig_repr, lower_s=None, compile_s=None):
        """Record one cold compile.  ``lower_s``/``compile_s`` split the
        cold-start cost into trace->StableHLO and XLA-compile phases
        (CachedProgram._acquire times them); they accumulate into the
        ``compile_s_total``/``lower_s_total`` counters so mxtop's CACHE
        line can show the fleet's cold-compile debt in seconds, not
        just counts."""
        with self._lock:
            self.counters["compiles"] += 1
            if compile_s is not None:
                self.counters["compile_s_total"] = round(
                    self.counters.get("compile_s_total", 0.0) +
                    float(compile_s), 3)
            if lower_s is not None:
                self.counters["lower_s_total"] = round(
                    self.counters.get("lower_s_total", 0.0) +
                    float(lower_s), 3)
            if len(self.events) < 512:
                ev = {"label": label, "signature": sig_repr}
                if lower_s is not None:
                    ev["lower_s"] = round(float(lower_s), 4)
                if compile_s is not None:
                    ev["compile_s"] = round(float(compile_s), 4)
                self.events.append(ev)

    def bump(self, counter, n=1):
        with self._lock:
            self.counters[counter] = self.counters.get(counter, 0) + n

    def register_program(self, program):
        import weakref
        with self._lock:
            self._programs.append(weakref.ref(program))

    def programs(self):
        """Live CachedPrograms registered with this cache."""
        with self._lock:
            refs = list(self._programs)
        out = []
        for r in refs:
            p = r()
            if p is not None:
                out.append(p)
        return out

    def stats(self):
        """One dict: global counters + per-program signature/compile
        breakdown (the mxlint cache-report's and bench's currency).
        Memory-hit counts live on the programs (the warm dispatch path
        is lock-free) and are aggregated here."""
        with self._lock:
            counters = dict(self.counters)
            events = list(self.events)
        progs = []
        mem_hits = 0
        for p in self.programs():
            mem_hits += p.mem_hits
            progs.append({
                "label": p.label,
                "signatures": len(p.signatures()),
                "compiles": p.compile_count,
                "disk_hits": p.disk_hits,
                "disk_misses": getattr(p, "disk_misses", 0),
                "mem_hits": p.mem_hits,
                "lower_s": round(getattr(p, "lower_s_total", 0.0), 4),
                "compile_s": round(getattr(p, "compile_s_total", 0.0), 4),
            })
        counters["mem_hits"] = counters.get("mem_hits", 0) + mem_hits
        lookups = counters["compiles"] + counters["mem_hits"] + \
            counters["disk_hits"] + counters.get("live_hits", 0)
        return {
            "counters": counters,
            "hit_rate": round((counters["mem_hits"] +
                               counters["disk_hits"] +
                               counters.get("live_hits", 0)) / lookups, 4)
            if lookups else None,
            "disk_enabled": self.enabled(),
            "directory": self.directory,
            "programs": progs,
            "compile_events": events,
        }

    def write_stats(self, path=None):
        """Append this process's stats record to ``stats.json`` next to
        the entries (read-modify-write, atomic publish, capped history)
        so offline tools — mxlint --cache-report — can aggregate hit
        rates across runs."""
        if path is None:
            if self.directory is None:
                return None
            path = os.path.join(os.path.dirname(self.directory),
                                "stats.json")
        record = self.stats()
        record.pop("compile_events", None)
        record["events"] = [e for e in self.events][:256]
        import time
        record["time"] = int(time.time())
        runs = []
        try:
            with open(path) as f:
                runs = json.load(f).get("runs", [])
        except (OSError, ValueError):
            pass
        runs = (runs + [record])[-50:]
        tmp = path + ".tmp%d" % os.getpid()
        try:
            with open(tmp, "w") as f:
                json.dump({"runs": runs}, f, indent=1)
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
        return path
