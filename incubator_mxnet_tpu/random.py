"""Framework RNG state.

Reference: per-device stateful generators behind ResourceManager
(`src/resource.cc:87-160`, `include/mxnet/random_generator.h`) seeded by
`mx.random.seed`.  TPU-native: one threefry key chain; every random op call
consumes a split subkey (`ops/random_ops.py`).  `seed()` resets the chain —
reproducible sequences, statistically (not bitwise) matching the reference.
"""
from __future__ import annotations

import threading

__all__ = ["seed", "next_key"]

_state = threading.local()


def _key():
    import jax
    if not hasattr(_state, "key"):
        _state.key = jax.random.PRNGKey(0)
    return _state.key


def seed(seed_state, ctx="all"):
    """Reset the global key chain (reference `python/mxnet/random.py:seed`).

    ``ctx`` is accepted for API parity; the key chain is global because
    threefry is counter-based — device independence comes for free.
    """
    import jax
    _state.key = jax.random.PRNGKey(int(seed_state))


def next_key():
    """Split and return a fresh subkey (internal: random-op dispatch)."""
    import jax
    k = _key()
    k, sub = jax.random.split(k)
    _state.key = k
    return sub
