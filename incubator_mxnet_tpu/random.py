"""Framework RNG state.

Reference: per-device stateful generators behind ResourceManager
(`src/resource.cc:87-160`, `include/mxnet/random_generator.h`) seeded by
`mx.random.seed`.  TPU-native: one threefry key chain; every random op call
consumes a split subkey (`ops/random_ops.py`).  `seed()` resets the chain —
reproducible sequences, statistically (not bitwise) matching the reference.
"""
from __future__ import annotations

import threading

__all__ = ["seed", "next_key"]

_state = threading.local()


def _key():
    import jax
    if not hasattr(_state, "key"):
        _state.key = jax.random.PRNGKey(0)
    return _state.key


def seed(seed_state, ctx="all"):
    """Reset the global key chain (reference `python/mxnet/random.py:seed`).

    ``ctx`` is accepted for API parity; the key chain is global because
    threefry is counter-based — device independence comes for free.
    """
    import jax
    _state.key = jax.random.PRNGKey(int(seed_state))
    _state.host_seq = [int(seed_state), 0]


def next_key():
    """Split and return a fresh subkey (internal: random-op dispatch)."""
    import jax
    k = _key()
    k, sub = jax.random.split(k)
    _state.key = k
    return sub


def host_rng():
    """numpy Generator for host-side draws (initializers), reproducible
    under `mx.random.seed(n)` like the reference's seeded mt19937 resource
    (`src/resource.cc:87-160`).  Purely host-side — a (seed, counter)
    SeedSequence, NOT a draw from the device key chain: initializing a
    large model must not issue one device round trip per parameter on a
    high-latency transport."""
    import numpy as np
    seq = getattr(_state, "host_seq", None)
    if seq is None:
        # never-seeded: draw the base from OS entropy (the reference's
        # mt19937 resource seeds non-deterministically by default too) —
        # a fixed (0, 0) base would make every unseeded process produce
        # byte-identical "random" initializations.  Note: np.random.seed()
        # does NOT influence this stream; use mx.random.seed() (README).
        seq = _state.host_seq = [
            int(np.random.SeedSequence().entropy % (2 ** 63)), 0]
    seq[1] += 1
    return np.random.default_rng(np.random.SeedSequence(tuple(seq)))
