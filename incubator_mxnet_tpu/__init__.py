"""incubator_mxnet_tpu — a TPU-native framework with MXNet's capabilities.

Brand-new implementation (NOT a port) of the Apache MXNet API surface
(reference: yieldbot/incubator-mxnet ~v1.2) on JAX/XLA/PJRT/Pallas:

* `nd` — async NDArray data plane in TPU HBM (PJRT buffers)
* `sym` + executors — symbolic graphs compiled to single XLA computations
* `autograd` — eager tape with XLA-compiled vjps
* `gluon` — imperative-first API; `hybridize()` = trace-to-XLA JIT
* `kvstore` — push/pull as collectives over the ICI mesh
* `module`/`mod` — classic symbolic training API
* `io`/`recordio` — high-throughput input pipeline

Typical use: ``import incubator_mxnet_tpu as mx``.
"""
from __future__ import annotations

__version__ = "0.1.0"

from .attribute import AttrScope
from .base import MXNetError
from . import analysis
from .context import Context, cpu, gpu, tpu, current_context, num_gpus, num_tpus
from . import config
from . import engine
from . import random
from . import autograd
from . import ndarray
from . import ndarray as nd
from .ndarray.ndarray import NDArray

# re-attach registry methods now that all op modules are loaded
from .ndarray.ndarray import _attach_methods as _am
_am()

# Layered subsystems import in dependency order; each guard block is removed
# as the subsystem lands (round-1 build plan, SURVEY.md §7).
import importlib as _importlib

for _mod_name, _aliases in [
    ("symbol", ("sym",)), ("executor", ()), ("initializer", ()),
    ("optimizer", ()), ("lr_scheduler", ()), ("metric", ()),
    ("kvstore", ("kv",)), ("callback", ()), ("monitor", ()),
    ("io", ()), ("recordio", ()), ("gluon", ()), ("module", ("mod",)),
    ("model", ()), ("profiler", ()), ("visualization", ("viz",)),
    ("parallel", ()), ("test_utils", ()), ("image", ()), ("operator", ()),
    ("contrib", ()), ("rnn", ()), ("compat", ()), ("dist", ()),
    ("subgraph", ()), ("storage", ()), ("libinfo", ()),
    ("checkpoint", ()), ("serving", ()), ("resilience", ()),
    ("kvstore_server", ()), ("native", ()), ("compile", ()),
    ("obs", ()), ("embedding", ()), ("loop", ()),
]:
    try:
        _m = _importlib.import_module("." + _mod_name, __name__)
    except ModuleNotFoundError as _e:
        if _e.name and _e.name.endswith(_mod_name):
            continue  # subsystem not yet built this round
        raise
    globals()[_mod_name] = _m
    for _a in _aliases:
        globals()[_a] = _m

if "symbol" in globals():
    from .symbol.symbol import Symbol  # noqa: E402
if "initializer" in globals():
    init = initializer  # noqa: F821
if "optimizer" in globals():
    from .optimizer import Optimizer  # noqa: E402

rnd = random

# env-var knobs that act at import time (config.py documents the full table)
config.apply_startup_knobs()
