"""KVStore: data-parallel parameter synchronization.

Re-expression of `src/kvstore/` (reference: `kvstore_local.h` CPU reduce,
`comm.h` CommCPU/CommDevice P2P reduce, `kvstore_nccl.h`, ps-lite
`kvstore_dist.h`) for TPU.  The API (Init/Push/Pull/set_updater/
set_optimizer, `kvstore.py` python surface) is preserved; the transport
changes per the BASELINE north star:

* ``local``  — reduce on host (CommCPU analogue)
* ``device``/``nccl`` — reduce on the accelerator (CommDevice/NCCL analogue)
* ``tpu``   — reduce as an XLA `psum` over the ICI device mesh: pushed
  per-device shards are donated to one fused all-reduce computation
  (replaces NCCL rings / PCIe spanning trees — `gpu_topology.h` is subsumed
  by XLA's collective scheduling on the torus)
* ``dist_sync``/``dist_async``/``dist_device_sync`` — multi-host via
  `jax.distributed` when initialized (each host reduces its local devices,
  then a global collective); in single-process runs they behave as ``device``
  with dist bookkeeping (rank/num_workers), which is exactly how the
  reference's nightly tests run multi-worker on localhost.

Gradient compression (reference `gradient_compression.h:52-134` 2-bit with
error feedback) is implemented in the push path with per-key residuals.
"""
from __future__ import annotations

import pickle
import weakref

import numpy as _np

from .base import MXNetError
from .context import Context, cpu, tpu, num_gpus
from .ndarray.ndarray import NDArray
from . import optimizer as opt

__all__ = ["KVStore", "create", "live_stats", "findings"]

# live collective stores (weak): analysis.runtime_report() and the bench/
# scaling tools read their stats() without holding the stores alive
_LIVE_STORES = weakref.WeakSet()


def live_stats():
    """stats() of every live collective (tpu/device) store — the
    scaling-bench artifact's and runtime_report's read path."""
    out = []
    for kv in list(_LIVE_STORES):
        try:
            out.append(kv.stats())
        except Exception:
            pass
    return out


def findings():
    """Bucketed-communication findings for `analysis.runtime_report()`:
    one HINT per live collective store summarizing its dispatch economy
    (collectives per push must be O(buckets), never O(params))."""
    from .analysis.findings import Finding, HINT
    out = []
    for st in live_stats():
        if not st.get("batched_pushes"):
            continue
        out.append(Finding(
            "kvstore.buckets", "summary", HINT,
            "kvstore='%s': %d batched pushes, %d allreduce dispatches "
            "(%.2f buckets/push, cap %d MB, avg fill %.0f%%, overlap "
            "%.0f%%), %.1f MB reduced"
            % (st["type"], st["batched_pushes"],
               st["allreduce_dispatches"],
               st["allreduce_dispatches"] / max(1, st["batched_pushes"]),
               st["bucket_cap_mb"], 100.0 * st["avg_bucket_fill"],
               100.0 * st["overlap_ratio"],
               st["bytes_reduced"] / (1 << 20)),
            location="kvstore"))
    return out


def _key(k):
    return str(k)


def plan_buckets(order, sizes, dtypes, cap_bytes):
    """THE bucket planning rule, shared by the kvstore scheduler
    (`KVStoreTPU._plan_buckets`) and the fused step's in-graph pod
    exchange (`fused._pod_bucket_psum`): pack the indices in `order`
    (already priority-sorted) into size-capped single-dtype buckets; an
    item larger than the cap gets a bucket of its own.  Deterministic —
    a pure function of (order, sizes, dtypes, cap), so two identical
    runs cut identical bucket boundaries, and the in-graph plan can
    never drift from the kvstore plan."""
    buckets, cur, cur_bytes, cur_dtype = [], [], 0, None
    for i in order:
        nb = sizes[i]
        if cur and (cur_bytes + nb > cap_bytes or dtypes[i] != cur_dtype):
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nb
        cur_dtype = dtypes[i]
    if cur:
        buckets.append(cur)
    return buckets


def _split_closure(shapes):
    """The flatten-concat inverse: a closure slicing a 1-D bucket
    payload back into `shapes` (shared by the reduce and pull split
    programs, which differ only in their jit wrapper)."""
    import jax
    sizes = [int(_np.prod(s)) if s else 1 for s in shapes]
    offs = _np.cumsum([0] + sizes)

    def _split(buf, shapes=shapes, offs=offs, sizes=sizes):
        return tuple(
            jax.lax.dynamic_slice_in_dim(
                buf, int(offs[k]), sizes[k]).reshape(shapes[k])
            for k in range(len(shapes)))
    return _split


class KVStore:
    """Single-process key-value store (reference `include/mxnet/kvstore.h:59-310`)."""

    def __init__(self, kind="local"):
        self._kind = kind
        self._store = {}        # key -> NDArray (on store device)
        self._updater = None
        self._optimizer = None
        self._compression = None
        self._residuals = {}
        if kind in ("device", "nccl", "tpu") and num_gpus() > 0:
            self._store_ctx = tpu(0)
        else:
            self._store_ctx = cpu(0)

    # -- identity ------------------------------------------------------------
    @property
    def type(self):
        return self._kind

    @property
    def rank(self):
        return self._dist_rank() if "dist" in self._kind else 0

    @property
    def num_workers(self):
        return self._dist_size() if "dist" in self._kind else 1

    @staticmethod
    def _dist_rank():
        import jax
        try:
            return jax.process_index()
        except Exception:
            return 0

    @staticmethod
    def _dist_size():
        import jax
        try:
            return jax.process_count()
        except Exception:
            return 1

    # -- init/push/pull --------------------------------------------------------
    def init(self, key, value):
        """Reference `kvstore.py init`."""
        keys, values = _normalize(key, value)
        for k, v in zip(keys, values):
            if _key(k) in self._store:
                raise MXNetError(f"Key {k} already initialized")
            self._store[_key(k)] = v.copyto(self._store_ctx)

    def _commit(self, k, merged):
        """Apply a reduced value to the store: updater when installed,
        else overwrite (shared by per-key and batched push paths)."""
        sk = _key(k)
        if self._updater is not None:
            self._updater(_updater_key(k), merged, self._store[sk])
        else:
            self._store[sk]._set_data(
                merged.copyto(self._store_ctx)._data.astype(
                    self._store[sk].dtype))

    def push(self, key, value, priority=0):
        """Push values; multi-device lists are reduced (summed) first
        (reference `kvstore_local.h:184 PushImpl` → `comm.h Reduce`)."""
        keys, values = _normalize_push(key, value)
        for k, vals in zip(keys, values):
            sk = _key(k)
            if sk not in self._store:
                raise MXNetError(f"Key {k} has not been initialized")
            merged = self._reduce(vals)
            if self._compression is not None:
                merged = self._compress(sk, merged)
            self._commit(k, merged)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """Broadcast stored value to out arrays (reference `comm.h:209 Broadcast`)."""
        if out is None:
            raise MXNetError("pull requires out=")
        keys, outs = _normalize_push(key, out)
        for k, tgt_list in zip(keys, outs):
            sk = _key(k)
            if sk not in self._store:
                raise MXNetError(f"Key {k} has not been initialized")
            src = self._store[sk]
            for tgt in tgt_list:
                src.copyto(tgt)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows in row_ids (reference `PullRowSparse`,
        `kvstore.py:314`).  Host-side gather (sparse is host-resident, see
        ndarray/sparse.py design note)."""
        if out is None or row_ids is None:
            raise MXNetError("row_sparse_pull requires out= and row_ids=")
        keys, outs = _normalize_push(key, out)
        if isinstance(row_ids, NDArray):
            row_ids = [row_ids]
        for k, tgt_list in zip(keys, outs):
            src = self._store[_key(k)]
            for tgt, rids in zip(tgt_list, row_ids * len(tgt_list)):
                rows = rids.asnumpy().astype("int64")
                vals = src.asnumpy()[rows]
                from .ndarray.sparse import RowSparseNDArray
                if isinstance(tgt, RowSparseNDArray):
                    tgt._np_data = vals
                    tgt._np_indices = rows
                else:
                    full = _np.zeros(src.shape, vals.dtype)
                    full[rows] = vals
                    tgt._set_data(tgt._data * 0 + full)

    def embedding(self, name, num_rows, dim, **kwargs):
        """A `embedding.ShardedEmbedding` table hosted on this store's
        parameter servers (dist stores only: the table's row shards live
        in the server processes, never densely on a worker).  Local
        stores have no server plane to shard onto."""
        raise MXNetError(
            f"kvstore type {self.type!r} has no parameter-server plane "
            "to host a sharded embedding — create the table against a "
            "'dist_async'/'dist_sync' store, or pass explicit server "
            "addresses to embedding.ShardedEmbedding")

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out=out, priority=priority)

    # -- reduction backends -----------------------------------------------------
    def _reduce(self, vals):
        if len(vals) == 1:
            return vals[0]
        import jax
        import jax.numpy as jnp
        if self._kind == "local":
            dev = cpu(0).jax_device
        else:
            dev = vals[0].context.jax_device
        acc = jax.device_put(vals[0]._data, dev)
        for v in vals[1:]:
            acc = acc + jax.device_put(v._data, dev)
        return NDArray(acc, ctx=vals[0].context if self._kind != "local" else cpu(0))

    # -- gradient compression ----------------------------------------------------
    def set_gradient_compression(self, compression_params):
        """2-bit compression with error feedback (reference
        `gradient_compression.h:52-134`).  None/empty clears it."""
        if not compression_params:
            self._compression = None
            self._residuals = {}
            return
        ctype = compression_params.get("type", "2bit")
        if ctype != "2bit":
            raise MXNetError("only 2bit gradient compression is supported "
                             "(as the reference)")
        self._compression = {
            "type": ctype,
            "threshold": float(compression_params.get("threshold", 0.5)),
        }

    def _compress(self, sk, merged):
        import jax
        import jax.numpy as jnp
        thr = self._compression["threshold"]
        resid = self._residuals.get(sk)
        g = merged._data
        if resid is not None:
            # the residual may have been written by the bucketed path on
            # a different device; device_put is a no-op when co-located
            if hasattr(resid, "devices") and hasattr(g, "devices") and \
                    resid.devices() != g.devices():
                resid = jax.device_put(resid, next(iter(g.devices())))
            g = g + resid
        q = jnp.where(g >= thr, thr, jnp.where(g <= -thr, -thr, 0.0)).astype(g.dtype)
        self._residuals[sk] = g - q
        return NDArray(q, ctx=merged.context)

    # -- optimizer integration ----------------------------------------------------
    def set_updater(self, updater):
        self._updater = updater

    _set_updater = set_updater

    def set_optimizer(self, optimizer):
        """Reference `kvstore.py set_optimizer`: in dist mode the reference
        pickles the optimizer to the servers; here the updater runs in-process
        on the reducing device."""
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    # -- server-state (de)serialization parity ------------------------------------
    def get_optimizer_states(self, dump_optimizer=False):
        """Optimizer slots as one bytes blob (the checkpoint plane's
        capture point; `dist/kvstore_dist.py` overrides to pull state back
        from the parameter servers)."""
        if self._updater is None:
            raise MXNetError("Cannot save states for distributed training")
        return self._updater.get_states(dump_optimizer)

    def set_optimizer_states(self, blob):
        if self._updater is None:
            raise MXNetError("Cannot load states for distributed training")
        self._updater.set_states(blob)

    def save_optimizer_states(self, fname, dump_optimizer=False):
        with open(fname, "wb") as f:
            f.write(self.get_optimizer_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        with open(fname, "rb") as f:
            self.set_optimizer_states(f.read())

    def _barrier(self):
        """Single-process stores have nothing to synchronize: engine order
        already serializes per-buffer access (WaitToRead semantics).  The
        distributed subclasses override this with a REAL rendezvous
        (`dist/kvstore_dist.py`); a single-process store is never a valid
        stand-in for one — assert loudly if someone treats it as such."""
        if self.num_workers != 1:
            raise MXNetError(
                f"{type(self).__name__} reports num_workers="
                f"{self.num_workers} but has no distributed barrier — use "
                "kv.create('dist_sync'/'dist_async')")

    def _send_command_to_servers(self, head, body):
        """No server processes exist for single-process stores; commands
        are meaningful only on the dist subclasses (which override)."""
        if self.num_workers != 1:
            raise MXNetError(
                "no servers to command on a single-process kvstore")


def _updater_key(k):
    return int(k) if isinstance(k, int) or (isinstance(k, str) and
                                            k.isdigit()) else k


class KVStoreTPU(KVStore):
    """`kvstore='tpu'` — push/pull as bucketed all-reduce over the device
    mesh (BASELINE north star; replaces `comm.h:451 CommDevice` /
    `kvstore_nccl.h:285-402`, bucket scheduling per the MLPerf-pods
    recipe: size-capped buckets, last-produced gradients first).

    Push: a multi-key push is packed into size-capped buckets
    (``MXNET_KVSTORE_BUCKET_MB``) in PRIORITY order — reversed key order,
    because backward materializes the LAST layer's gradients first — and
    each bucket's flatten+concat + `shard_map(psum)` + split programs are
    dispatched asynchronously as the bucket fills: bucket k's collective
    executes on the devices while the host is still assembling bucket
    k+1 (the dependency-engine overlap re-expressed as async XLA
    dispatch).  All three programs per bucket signature are compiled
    through the unified program cache, so steady state never recompiles.
    `push_part`/`end_push` expose the same machinery as a streaming API
    for callers whose gradients materialize one at a time.

    Pull: the stored values are broadcast with one `device_put` per
    bucket onto a replicated `NamedSharding` over the same mesh (XLA's
    broadcast collective), and each target takes its local shard — again
    O(buckets) collectives rather than N point-to-point copies.

    2-bit gradient compression composes with bucketing: the quantize
    (pack) + error-feedback residual update runs INSIDE the bucket
    program on the reduced payload, elementwise-identical to the
    reference's per-key path (`gradient_compression.h:52-134`).
    """

    def __init__(self, kind="tpu"):
        super().__init__(kind)
        self._meshes = {}        # tuple(device ids) -> Mesh
        self._allreduce_jit = {}  # tuple(device ids) -> jitted shard_map psum
        # last mesh a key was pushed over; lets pull() reuse the same devices
        self._key_mesh = {}
        self._concat_jit = None  # lazy shared flatten+concat program
        self._split_jit = {}     # (device ids, shapes) -> split program
        self._quant_jit = None   # 2-bit quantize+residual program
        self._stream = None      # pending streaming-push state
        self._last_bucket_out = None   # overlap probe (is_ready)
        self.allreduce_dispatches = 0   # tests assert one per step
        self._counters = {
            "pushes": 0, "batched_pushes": 0, "bytes_reduced": 0,
            "buckets": 0, "fill_sum": 0.0, "overlap_hits": 0,
            "overlap_eligible": 0, "pull_broadcasts": 0,
            "fallback_reduces": 0,
        }
        self._fill_hist = [0, 0, 0, 0]   # fill quartiles (<=25..<=100%)
        _LIVE_STORES.add(self)
        # telemetry plane: the communication-economy counters under the
        # stable 'kvstore' namespace (weakly held; the newest live
        # store answers scrapes)
        from .obs import metrics as _obs_metrics
        _obs_metrics.register_producer("kvstore", self.stats)

    @property
    def _bucket_cap_bytes(self):
        from . import config as _config
        # fractional MB are honored (tests force multi-bucket plans on
        # KB-sized tensors); floor of 1 byte keeps the planner sane
        return max(1, int(float(_config.get("MXNET_KVSTORE_BUCKET_MB"))
                          * (1 << 20)))

    @property
    def _overlap_enabled(self):
        from . import config as _config
        return bool(_config.get("MXNET_KVSTORE_OVERLAP"))

    def stats(self):
        """Communication-economy counters of this store: allreduce
        dispatches, bytes reduced, bucket count/fill, overlap ratio —
        surfaced through `analysis.runtime_report()` and stamped into
        BENCH_SCALING.json by tools/run_scaling.py."""
        self._release_guard()
        c = self._counters
        return {
            "type": self._kind,
            "pushes": c["pushes"],
            "batched_pushes": c["batched_pushes"],
            "allreduce_dispatches": self.allreduce_dispatches,
            "bytes_reduced": c["bytes_reduced"],
            "buckets": c["buckets"],
            "bucket_cap_mb": self._bucket_cap_bytes / (1 << 20),
            "bucket_fill_hist": {
                "<=25%": self._fill_hist[0], "<=50%": self._fill_hist[1],
                "<=75%": self._fill_hist[2], "<=100%": self._fill_hist[3]},
            "avg_bucket_fill": c["fill_sum"] / max(1, c["buckets"]),
            "overlap_ratio": c["overlap_hits"] / max(1,
                                                     c["overlap_eligible"]),
            "pull_broadcasts": c["pull_broadcasts"],
            "fallback_reduces": c["fallback_reduces"],
            "compression": None if self._compression is None
            else dict(self._compression),
        }

    def predicted_stats(self, shapes, dtypes=None, ndev=None):
        """Static mirror of one batched push's `stats()` counters —
        the plan-introspection hook the mxcost analyzer cross-checks
        against measured numbers: given the key shapes (and dtypes) a
        batched push would carry, derive the bucket plan with the SAME
        `plan_buckets` rule and priority order the scheduler uses and
        return the predicted allreduce dispatches / bytes reduced /
        bucket count.  `analysis.cost.enumerate_collectives` does the
        arithmetic; this method just binds this store's live bucket cap
        and device count to it."""
        from .analysis import cost as _cost
        if ndev is None:
            import jax
            ndev = len(jax.devices())
        stats = _cost.enumerate_collectives(
            shapes, dtypes=dtypes, dp=ndev,
            cap_bytes=self._bucket_cap_bytes,
            name=f"kvstore-{self._kind}")
        return {
            "type": self._kind,
            "allreduce_dispatches": stats["collectives_per_step"],
            "bytes_reduced": stats["bytes_per_step"],
            "buckets": stats["buckets"],
            "bucket_cap_mb": stats["bucket_cap_mb"],
            "dispatch_complexity": stats["dispatch_complexity"],
            "plan": stats["plan"],
        }

    def _mesh_for(self, devices):
        ids = tuple(d.id for d in devices)
        mesh = self._meshes.get(ids)
        if mesh is None:
            import numpy as np
            from jax.sharding import Mesh
            mesh = Mesh(np.asarray(devices), ("dev",))
            self._meshes[ids] = mesh
        return mesh

    def _allreduce(self, mesh):
        """One jitted all-reduce over the mesh: (N, *s) sharded on 'dev'
        → summed (*s), replicated on every participating device."""
        ids = tuple(d.id for d in mesh.devices.flat)
        fn = self._allreduce_jit.get(ids)
        if fn is None:
            import jax
            from jax.sharding import PartitionSpec as P
            shard_map = getattr(jax, "shard_map", None)
            if shard_map is None:                     # older jax
                from jax.experimental.shard_map import shard_map

            def _psum(shards):           # shards: (1, *s) local block
                return jax.lax.psum(shards[0], "dev")

            fn = jax.jit(shard_map(_psum, mesh=mesh,
                                   in_specs=P("dev"), out_specs=P()))
            self._allreduce_jit[ids] = fn
        return fn

    def _reduce(self, vals):
        if len(vals) == 1:
            return vals[0]
        import jax
        devices = [v.context.jax_device for v in vals]
        if len({d.id for d in devices}) != len(devices):
            # duplicate devices (e.g. all values on one chip): plain sum
            acc = vals[0]._data
            for v in vals[1:]:
                acc = acc + jax.device_put(v._data, devices[0])
            return NDArray(acc, ctx=vals[0].context)
        mesh = self._mesh_for(devices)
        shape = tuple(vals[0].shape)
        return NDArray(
            self._mesh_allreduce(mesh, shape,
                                 [v._data for v in vals],
                                 vals[0].context.jax_device.id),
            ctx=vals[0].context)

    def _mesh_allreduce(self, mesh, shape, shards, lead_id):
        """Assemble per-device shards into one mesh array, psum with ONE
        collective, return the lead device's replicated shard (downstream
        single-device math sees an ordinary committed array; the pull path
        re-broadcasts with one collective)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        global_arr = jax.make_array_from_single_device_arrays(
            (len(shards),) + shape, NamedSharding(mesh, P("dev")),
            [b.reshape((1,) + shape) for b in shards])
        self.allreduce_dispatches += 1
        summed = self._allreduce(mesh)(global_arr)
        return next(s.data for s in summed.addressable_shards
                    if s.device.id == lead_id)

    def _record_key_mesh(self, sk, vals):
        """Remember the device set a key was pushed over so pull() can use
        the one-collective broadcast instead of per-target copies."""
        if len(vals) > 1:
            devs = [v.context.jax_device for v in vals]
            if len({d.id for d in devs}) == len(devs):
                self._key_mesh[sk] = self._mesh_for(devs)

    @property
    def prefers_batched_push(self):
        """Multi-key push/pull should arrive as one call: the whole key
        list reduces in O(buckets) collectives (`_reduce_many`) instead
        of one per parameter (the reference's batched NCCL push,
        `model.py:125`)."""
        return True

    # -- bucket planning -------------------------------------------------------
    @staticmethod
    def _nbytes(v):
        size = int(_np.prod(v.shape)) if v.shape else 1
        return size * _np.dtype(v.dtype).itemsize

    def _plan_buckets(self, order, values):
        """Pack the key indices in `order` (already priority-sorted:
        batched pushes reverse the key list because backward materializes
        the LAST layer's gradients first; streaming pushes arrive in
        production order) into size-capped single-dtype buckets.  A key
        larger than the cap gets a bucket of its own.  Deterministic:
        the plan is a pure function of (order, shapes, dtypes, cap), so
        two identical runs cut identical bucket boundaries."""
        return plan_buckets(
            order, [self._nbytes(v[0]) for v in values],
            [v[0].dtype for v in values], self._bucket_cap_bytes)

    # -- cached bucket programs ------------------------------------------------
    def _concat_prog(self, dev_id=None):
        if self._concat_jit is None:
            self._concat_jit = {}
        prog = self._concat_jit.get(dev_id)
        if prog is None:
            import jax.numpy as jnp
            from .compile import cached_jit
            # one shape-agnostic program PER DEVICE (an AOT executable
            # validates the input placement, so each device's flatten+
            # concat is its own cache entry); the per-signature cache
            # specializes per bucket signature (unified program cache —
            # steady state never recompiles)
            self._concat_jit[dev_id] = prog = cached_jit(
                lambda *xs: jnp.concatenate([x.reshape(-1) for x in xs]),
                graph_key=("kvstore-concat", dev_id),
                label="kvstore/concat")
        return prog

    def _split_prog(self, ids0, shapes):
        from .compile import cached_jit
        split = self._split_jit.get((ids0, shapes))
        if split is None:
            split = cached_jit(_split_closure(shapes),
                              graph_key=("kvstore-split", ids0, shapes),
                              label="kvstore/split")
            self._split_jit[(ids0, shapes)] = split
        return split

    def _pull_split(self, shapes):
        """Split program for the pull broadcast's per-device local
        shards: plain jit (its cache keys on the committed device, so
        the SAME shapes on 8 devices are 8 silent specializations —
        an AOT entry would reject 7 of them)."""
        import jax
        split = self._split_jit.get(("pull", shapes))
        if split is None:
            split = jax.jit(_split_closure(shapes))
            self._split_jit[("pull", shapes)] = split
        return split

    def _quant_prog(self):
        """2-bit quantize + error-feedback residual as ONE program on the
        reduced bucket payload (reference `gradient_compression.h:52-134`
        — elementwise, so the bucketed result is bit-identical to the
        per-key path).  The threshold rides as a traced scalar so
        changing it never recompiles."""
        if self._quant_jit is None:
            import jax.numpy as jnp
            from .compile import cached_jit

            def quant(g, resid, thr):
                t = jnp.asarray(thr, g.dtype)
                x = g + resid
                q = jnp.where(x >= t, t,
                              jnp.where(x <= -t, -t,
                                        jnp.zeros((), g.dtype)))
                return q, x - q
            self._quant_jit = cached_jit(quant,
                                         graph_key=("kvstore-2bit",),
                                         label="kvstore/2bit")
        return self._quant_jit

    # -- bucketed reduce -------------------------------------------------------
    def _reduce_bucket(self, idxs, keys, values, mesh, lead_id, ids0):
        """Reduce one bucket: per-device flatten+concat, ONE psum over
        the mesh, optional in-bucket 2-bit quantize, split back.  Every
        program dispatch here is ASYNC — the collective executes while
        the host assembles the next bucket (the overlap probe counts how
        often that actually happened, without ever blocking)."""
        import jax
        ndev = len(values[idxs[0]])
        shapes = tuple(tuple(values[i][0].shape) for i in idxs)
        dtype = values[idxs[0]][0].dtype
        total = int(sum(int(_np.prod(s)) if s else 1 for s in shapes))
        per_dev = [
            self._concat_prog(ids0[d])(*[values[i][d]._data for i in idxs])
            for d in range(ndev)]
        prev = self._last_bucket_out
        if prev is not None:
            self._counters["overlap_eligible"] += 1
            try:
                if not prev.is_ready():
                    self._counters["overlap_hits"] += 1
            except Exception:
                pass
            if mesh.devices.flat[0].platform == "cpu":
                # depth-1 collective pipeline on CPU hosts: XLA-CPU
                # collectives rendezvous on HOST threads, so two
                # all-reduce rounds in flight can interleave their
                # participants across a core-limited pool and deadlock
                # (each round holding threads the other needs).  Bucket
                # k+1's assembly above still overlapped bucket k's
                # collective; we just never keep TWO collectives queued.
                # On TPU the collective runs on device hardware and the
                # full pipeline depth stays async.
                jax.block_until_ready(prev)
        local = self._mesh_allreduce(mesh, (total,), per_dev, lead_id)
        nbytes = total * _np.dtype(dtype).itemsize
        self._counters["bytes_reduced"] += nbytes
        self._counters["buckets"] += 1
        fill = min(1.0, nbytes / self._bucket_cap_bytes)
        self._counters["fill_sum"] += fill
        self._fill_hist[min(3, max(0, int(_np.ceil(fill * 4)) - 1))] += 1
        if self._compression is not None:
            # the error-feedback residual lives PER KEY in the same
            # `_residuals` map the per-key fallback path uses (quantize
            # is elementwise, so the bucket residual is exactly the
            # concat of per-key residuals) — a mid-run switch between
            # the bucketed and fallback reduce paths keeps every key's
            # accumulated quantization error intact
            import jax.numpy as jnp
            dev = next(iter(local.devices()))
            parts = []
            for i, s in zip(idxs, shapes):
                r = self._residuals.get(_key(keys[i]))
                if r is None:
                    n = int(_np.prod(s)) if s else 1
                    parts.append(jnp.zeros((n,), dtype))
                else:
                    parts.append(jax.device_put(r, dev).reshape(-1))
            resid = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
            thr = _np.asarray(self._compression["threshold"], dtype)
            local, new_resid = self._quant_prog()(local, resid, thr)
            for i, p in zip(idxs, self._pull_split(shapes)(new_resid)):
                self._residuals[_key(keys[i])] = p
        self._last_bucket_out = local
        if not self._overlap_enabled:
            jax.block_until_ready(local)
        pieces = self._split_prog(ids0, shapes)(local)
        ctx0 = values[idxs[0]][0].context
        return {i: NDArray(p, ctx=ctx0) for i, p in zip(idxs, pieces)}

    def _bucket_eligible(self, values):
        first_devs = [v.context.jax_device for v in values[0]]
        ids0 = tuple(d.id for d in first_devs)
        same = all(tuple(v.context.jax_device.id for v in vals) == ids0
                   for vals in values)
        if not same or len(first_devs) == 1 or len(set(ids0)) != len(ids0):
            return None
        return first_devs, ids0

    def _reduce_ordered(self, order, keys, values):
        """Bucketed reduce of `values` in the given priority order;
        returns merged NDArrays aligned with `keys`.  Falls back to
        per-key reduction (with per-key compression) when the key list
        does not share one clean device mesh."""
        placed = self._bucket_eligible(values)
        if placed is None:
            self._counters["fallback_reduces"] += 1
            return [self._reduce_compress(keys[k], vals)
                    for k, vals in enumerate(values)]
        first_devs, ids0 = placed
        mesh = self._mesh_for(first_devs)
        self._counters["batched_pushes"] += 1
        results = {}
        # NOTE: _last_bucket_out deliberately carries over from the
        # previous push — the depth-1 CPU collective pipeline guard in
        # _reduce_bucket must also cover back-to-back pushes (push k's
        # final collective may still be in flight when push k+1
        # dispatches its first bucket)
        bytes_before = self._counters["bytes_reduced"]
        plan = self._plan_buckets(order, values)
        for bucket in plan:
            results.update(self._reduce_bucket(
                bucket, keys, values, mesh, first_devs[0].id, ids0))
        from . import profiler as _profiler
        _profiler.record_kvstore(
            "bucketed_push", keys=len(keys), buckets=len(plan),
            bytes=self._counters["bytes_reduced"] - bytes_before)
        return [results[i] for i in range(len(values))]

    def _reduce_compress(self, k, vals):
        merged = self._reduce(vals)
        if self._compression is not None:
            merged = self._compress(_key(k), merged)
        return merged

    def _reduce_many(self, values, keys=None):
        """Bucketed multi-key reduce (batched push): priority order is
        REVERSED key order — backward produces the last layer's
        gradients first, so their buckets dispatch first."""
        keys = list(keys) if keys is not None else list(range(len(values)))
        return self._reduce_ordered(list(reversed(range(len(values)))),
                                    keys, values)

    # -- streaming push: dispatch buckets as gradients materialize ------------
    def begin_push(self):
        """Open a streaming push: gradients arrive one key at a time
        (`push_part`) in production order as backward materializes them;
        every time the pending set reaches the bucket cap its reduce
        dispatches IMMEDIATELY, overlapping the rest of backward.
        `end_push` flushes the tail and closes the stream."""
        if self._stream is not None:
            raise MXNetError("begin_push: a streaming push is already open")
        self._stream = {"keys": [], "values": [], "bytes": 0}
        # _last_bucket_out carries over (see _reduce_ordered): the CPU
        # depth-1 pipeline guard spans push boundaries too

    def push_part(self, key, value, priority=0):
        """Add one (or more) keys' per-device gradients to the open
        streaming push; dispatches a bucket when the cap fills."""
        st = self._stream
        if st is None:
            raise MXNetError("push_part outside begin_push/end_push")
        keys, values = _normalize_push(key, value)
        for k, vals in zip(keys, values):
            sk = _key(k)
            if sk not in self._store:
                raise MXNetError(f"Key {k} has not been initialized")
            self._record_key_mesh(sk, vals)
            st["keys"].append(k)
            st["values"].append(vals)
            st["bytes"] += self._nbytes(vals[0])
        if st["bytes"] >= self._bucket_cap_bytes:
            self._flush_stream()

    def _flush_stream(self):
        st = self._stream
        keys, values = st["keys"], st["values"]
        if not keys:
            return
        st["keys"], st["values"], st["bytes"] = [], [], 0
        if all(len(vals) > 1 for vals in values):
            merged = self._reduce_ordered(list(range(len(keys))), keys,
                                          values)
        else:
            merged = [self._reduce_compress(k, vals)
                      for k, vals in zip(keys, values)]
        for k, m in zip(keys, merged):
            self._commit(k, m)

    def end_push(self):
        """Flush the pending tail of a streaming push and close it."""
        if self._stream is None:
            raise MXNetError("end_push without begin_push")
        try:
            self._flush_stream()
        finally:
            self._stream = None

    def push(self, key, value, priority=0):
        keys, values = _normalize_push(key, value)
        self._counters["pushes"] += 1
        for k, vals in zip(keys, values):
            self._record_key_mesh(_key(k), vals)
        if len(keys) > 1 and all(len(vals) > 1 for vals in values):
            for k in keys:
                if _key(k) not in self._store:
                    raise MXNetError(f"Key {k} has not been initialized")
            from .obs import trace as _obs_trace
            with _obs_trace.span("kvstore.push", cat="kvstore",
                                 keys=len(keys)):
                merged = self._reduce_many(values, keys)
                for k, m in zip(keys, merged):
                    self._commit(k, m)
            return
        super().push(key, value, priority)

    def set_gradient_compression(self, compression_params):
        """2-bit compression on the collective store COMPOSES with
        bucketing (quantize + error-feedback residual inside the bucket
        program); anything else is a structured unsupported error — the
        base-class stub would otherwise half-apply it silently.
        None/empty clears compression (handled by the base class)."""
        if not compression_params:
            return super().set_gradient_compression(compression_params)
        ctype = compression_params.get("type", "2bit")
        if ctype != "2bit":
            raise MXNetError(
                f"kvstore='{self._kind}': gradient compression type "
                f"{ctype!r} is unsupported on the collective store — only "
                "'2bit' (in-bucket quantize with error feedback) composes "
                "with bucketed all-reduce")
        super().set_gradient_compression(compression_params)

    def _release_guard(self):
        """Drop the pipeline-guard reference once its collective has
        finished: a completed bucket can never be the second-in-flight
        collective the depth-1 CPU guard exists to prevent, and holding
        it longer pins a bucket-sized device buffer for no reason."""
        prev = self._last_bucket_out
        if prev is not None:
            try:
                if prev.is_ready():
                    self._last_bucket_out = None
            except Exception:
                self._last_bucket_out = None

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        if out is None:
            raise MXNetError("pull requires out=")
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        self._release_guard()
        keys, outs = _normalize_push(key, out)
        for k in keys:
            if _key(k) not in self._store:
                raise MXNetError(f"Key {k} has not been initialized")
        # bucketed broadcast: the multi-key pull mirroring a bucketed
        # push rides O(buckets) broadcast collectives (concat the stored
        # values, ONE device_put onto the replicated mesh sharding per
        # bucket, split each device's local shard) instead of one
        # transfer per key
        remaining = list(range(len(keys)))
        if len(keys) > 1:
            remaining = self._pull_buckets(keys, outs)
        for i in remaining:
            k, tgt_list = keys[i], outs[i]
            sk = _key(k)
            src = self._store[sk]
            mesh = self._key_mesh.get(sk)
            tgt_devs = {t.context.jax_device.id for t in tgt_list}
            mesh_devs = ({d.id for d in mesh.devices.flat}
                         if mesh is not None else set())
            if mesh is not None and len(tgt_list) > 1 and \
                    tgt_devs <= mesh_devs:
                # one broadcast collective over the mesh, then local shards
                rep = jax.device_put(src._data, NamedSharding(mesh, P()))
                self._counters["pull_broadcasts"] += 1
                by_dev = {s.device.id: s.data for s in rep.addressable_shards}
                for tgt in tgt_list:
                    tgt._set_data(by_dev[tgt.context.jax_device.id]
                                  .astype(tgt.dtype))
            else:
                for tgt in tgt_list:
                    src.copyto(tgt)

    def _pull_buckets(self, keys, outs):
        """Broadcast every eligible key in size-capped buckets; returns
        the indices the caller must still pull per-key.  Eligible: >1
        targets, every key on ONE shared recorded mesh, targets within
        it, store values and targets dtype-consistent per bucket."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        cand = {}   # key index -> its recorded mesh
        for i, (k, tgt_list) in enumerate(zip(keys, outs)):
            m = self._key_mesh.get(_key(k))
            if m is not None and len(tgt_list) >= 2:
                cand[i] = m
        if not cand:
            return list(range(len(keys)))
        # the MAJORITY mesh keeps the O(buckets) economy even when one
        # leading key was recorded on a different (minority) mesh — that
        # key just falls to the per-key path below
        counts = {}
        for m in cand.values():
            counts[id(m)] = counts.get(id(m), 0) + 1
        mesh = max(cand.values(), key=lambda m: counts[id(m)])
        mesh_devs = {d.id for d in mesh.devices.flat}
        elig = []
        for i, m in cand.items():
            sk = _key(keys[i])
            if m is mesh and \
                    {t.context.jax_device.id for t in outs[i]} <= \
                    mesh_devs and \
                    all(t.dtype == self._store[sk].dtype
                        for t in outs[i]):
                elig.append(i)
        if len(elig) < 2:
            return list(range(len(keys)))
        values = [[self._store[_key(keys[i])]] for i in elig]
        cat = self._concat_prog(self._store_ctx.jax_device.id)
        rep_sharding = NamedSharding(mesh, P())
        for bucket in self._plan_buckets(range(len(elig)), values):
            idxs = [elig[j] for j in bucket]
            shapes = tuple(tuple(self._store[_key(keys[i])].shape)
                           for i in idxs)
            buf = cat(*[self._store[_key(keys[i])]._data for i in idxs])
            rep = jax.device_put(buf, rep_sharding)
            self._counters["pull_broadcasts"] += 1
            split = self._pull_split(shapes)
            by_dev = {s.device.id: split(s.data)
                      for s in rep.addressable_shards}
            for j, i in enumerate(idxs):
                for tgt in outs[i]:
                    tgt._set_data(
                        by_dev[tgt.context.jax_device.id][j])
        return [i for i in range(len(keys)) if i not in set(elig)]


def _normalize(key, value):
    if isinstance(key, (int, str)):
        keys = [key]
        values = [value if isinstance(value, NDArray) else value]
    else:
        keys = list(key)
        values = list(value)
    return keys, values


def _normalize_push(key, value):
    """Returns keys + list-of-lists of arrays."""
    if isinstance(key, (int, str)):
        if isinstance(value, NDArray):
            return [key], [[value]]
        if isinstance(value, (list, tuple)) and value and isinstance(
                value[0], NDArray):
            return [key], [list(value)]
        raise MXNetError("invalid push/pull value")
    keys = list(key)
    out = []
    for v in value:
        if isinstance(v, NDArray):
            out.append([v])
        else:
            out.append(list(v))
    return keys, out


def create(name="local"):
    """Factory (reference `src/kvstore/kvstore.cc:48-64` type dispatch)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    if name == "tpu":
        return KVStoreTPU()
    if name in ("device", "nccl", "local_allreduce_device"):
        # device-side reduce: same single-collective engine as 'tpu'
        # (reference comm.h CommDevice / kvstore_nccl.h both lower to one
        # all-reduce; so do we)
        return KVStoreTPU("device")
    if name in ("local", "local_allreduce_cpu"):
        return KVStore("local")
    if name in ("dist_sync", "dist_async", "dist_device_sync",
                "dist_sync_device", "dist"):
        import os
        role = os.environ.get("DMLC_ROLE")
        if role == "server":
            # the reference runs the same user script on server hosts; the
            # process becomes the server and never returns to user code
            # (python/mxnet/kvstore_server.py _init_kvstore_server_module).
            # Constraint vs the reference: one server, colocated with the
            # root URI host (gradient traffic rides the TPU mesh, the
            # server is control-plane only).
            import sys
            from .dist.server import ParameterServer
            ParameterServer(
                host=os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1"),
                port=int(os.environ.get("DMLC_PS_ROOT_PORT", 9091)),
            ).serve_forever()
            sys.exit(0)
        if role == "scheduler":
            # no scheduler in this architecture (no rendezvous needed: the
            # single server's address is static); exit cleanly so external
            # trackers that spawn one are satisfied
            import sys
            sys.exit(0)
        if os.environ.get("DMLC_PS_ROOT_URI") or role == "worker":
            from .dist.kvstore_dist import KVStoreDist
            return KVStoreDist(name)
        # no tracker env: single-process stand-in with dist bookkeeping
        # (how the reference's unit tests run dist kvstores too)
        return KVStore(name)
    raise MXNetError(f"Unknown KVStore type {name}")
