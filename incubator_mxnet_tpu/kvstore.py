"""KVStore: data-parallel parameter synchronization.

Re-expression of `src/kvstore/` (reference: `kvstore_local.h` CPU reduce,
`comm.h` CommCPU/CommDevice P2P reduce, `kvstore_nccl.h`, ps-lite
`kvstore_dist.h`) for TPU.  The API (Init/Push/Pull/set_updater/
set_optimizer, `kvstore.py` python surface) is preserved; the transport
changes per the BASELINE north star:

* ``local``  — reduce on host (CommCPU analogue)
* ``device``/``nccl`` — reduce on the accelerator (CommDevice/NCCL analogue)
* ``tpu``   — reduce as an XLA `psum` over the ICI device mesh: pushed
  per-device shards are donated to one fused all-reduce computation
  (replaces NCCL rings / PCIe spanning trees — `gpu_topology.h` is subsumed
  by XLA's collective scheduling on the torus)
* ``dist_sync``/``dist_async``/``dist_device_sync`` — multi-host via
  `jax.distributed` when initialized (each host reduces its local devices,
  then a global collective); in single-process runs they behave as ``device``
  with dist bookkeeping (rank/num_workers), which is exactly how the
  reference's nightly tests run multi-worker on localhost.

Gradient compression (reference `gradient_compression.h:52-134` 2-bit with
error feedback) is implemented in the push path with per-key residuals.
"""
from __future__ import annotations

import pickle

import numpy as _np

from .base import MXNetError
from .context import Context, cpu, tpu, num_gpus
from .ndarray.ndarray import NDArray
from . import optimizer as opt

__all__ = ["KVStore", "create"]


def _key(k):
    return str(k)


class KVStore:
    """Single-process key-value store (reference `include/mxnet/kvstore.h:59-310`)."""

    def __init__(self, kind="local"):
        self._kind = kind
        self._store = {}        # key -> NDArray (on store device)
        self._updater = None
        self._optimizer = None
        self._compression = None
        self._residuals = {}
        if kind in ("device", "nccl", "tpu") and num_gpus() > 0:
            self._store_ctx = tpu(0)
        else:
            self._store_ctx = cpu(0)

    # -- identity ------------------------------------------------------------
    @property
    def type(self):
        return self._kind

    @property
    def rank(self):
        return self._dist_rank() if "dist" in self._kind else 0

    @property
    def num_workers(self):
        return self._dist_size() if "dist" in self._kind else 1

    @staticmethod
    def _dist_rank():
        import jax
        try:
            return jax.process_index()
        except Exception:
            return 0

    @staticmethod
    def _dist_size():
        import jax
        try:
            return jax.process_count()
        except Exception:
            return 1

    # -- init/push/pull --------------------------------------------------------
    def init(self, key, value):
        """Reference `kvstore.py init`."""
        keys, values = _normalize(key, value)
        for k, v in zip(keys, values):
            if _key(k) in self._store:
                raise MXNetError(f"Key {k} already initialized")
            self._store[_key(k)] = v.copyto(self._store_ctx)

    def push(self, key, value, priority=0):
        """Push values; multi-device lists are reduced (summed) first
        (reference `kvstore_local.h:184 PushImpl` → `comm.h Reduce`)."""
        keys, values = _normalize_push(key, value)
        for k, vals in zip(keys, values):
            sk = _key(k)
            if sk not in self._store:
                raise MXNetError(f"Key {k} has not been initialized")
            merged = self._reduce(vals)
            if self._compression is not None:
                merged = self._compress(sk, merged)
            if self._updater is not None:
                self._updater(_updater_key(k), merged, self._store[sk])
            else:
                self._store[sk]._set_data(
                    merged.copyto(self._store_ctx)._data.astype(
                        self._store[sk].dtype))

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """Broadcast stored value to out arrays (reference `comm.h:209 Broadcast`)."""
        if out is None:
            raise MXNetError("pull requires out=")
        keys, outs = _normalize_push(key, out)
        for k, tgt_list in zip(keys, outs):
            sk = _key(k)
            if sk not in self._store:
                raise MXNetError(f"Key {k} has not been initialized")
            src = self._store[sk]
            for tgt in tgt_list:
                src.copyto(tgt)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows in row_ids (reference `PullRowSparse`,
        `kvstore.py:314`).  Host-side gather (sparse is host-resident, see
        ndarray/sparse.py design note)."""
        if out is None or row_ids is None:
            raise MXNetError("row_sparse_pull requires out= and row_ids=")
        keys, outs = _normalize_push(key, out)
        if isinstance(row_ids, NDArray):
            row_ids = [row_ids]
        for k, tgt_list in zip(keys, outs):
            src = self._store[_key(k)]
            for tgt, rids in zip(tgt_list, row_ids * len(tgt_list)):
                rows = rids.asnumpy().astype("int64")
                vals = src.asnumpy()[rows]
                from .ndarray.sparse import RowSparseNDArray
                if isinstance(tgt, RowSparseNDArray):
                    tgt._np_data = vals
                    tgt._np_indices = rows
                else:
                    full = _np.zeros(src.shape, vals.dtype)
                    full[rows] = vals
                    tgt._set_data(tgt._data * 0 + full)

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out=out, priority=priority)

    # -- reduction backends -----------------------------------------------------
    def _reduce(self, vals):
        if len(vals) == 1:
            return vals[0]
        import jax
        import jax.numpy as jnp
        if self._kind == "local":
            dev = cpu(0).jax_device
        else:
            dev = vals[0].context.jax_device
        acc = jax.device_put(vals[0]._data, dev)
        for v in vals[1:]:
            acc = acc + jax.device_put(v._data, dev)
        return NDArray(acc, ctx=vals[0].context if self._kind != "local" else cpu(0))

    # -- gradient compression ----------------------------------------------------
    def set_gradient_compression(self, compression_params):
        """2-bit compression with error feedback (reference
        `gradient_compression.h:52-134`)."""
        ctype = compression_params.get("type", "2bit")
        if ctype != "2bit":
            raise MXNetError("only 2bit gradient compression is supported "
                             "(as the reference)")
        self._compression = {
            "type": ctype,
            "threshold": float(compression_params.get("threshold", 0.5)),
        }

    def _compress(self, sk, merged):
        import jax.numpy as jnp
        thr = self._compression["threshold"]
        resid = self._residuals.get(sk)
        g = merged._data
        if resid is not None:
            g = g + resid
        q = jnp.where(g >= thr, thr, jnp.where(g <= -thr, -thr, 0.0)).astype(g.dtype)
        self._residuals[sk] = g - q
        return NDArray(q, ctx=merged.context)

    # -- optimizer integration ----------------------------------------------------
    def set_updater(self, updater):
        self._updater = updater

    _set_updater = set_updater

    def set_optimizer(self, optimizer):
        """Reference `kvstore.py set_optimizer`: in dist mode the reference
        pickles the optimizer to the servers; here the updater runs in-process
        on the reducing device."""
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    # -- server-state (de)serialization parity ------------------------------------
    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("Cannot save states for distributed training")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("Cannot load states for distributed training")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def _barrier(self):
        pass

    def _send_command_to_servers(self, head, body):
        pass


def _updater_key(k):
    return int(k) if isinstance(k, int) or (isinstance(k, str) and
                                            k.isdigit()) else k


class KVStoreTPU(KVStore):
    """`kvstore='tpu'` — push/pull as one fused all-reduce over the device
    mesh (BASELINE north star).  For list-of-device-arrays pushes the reduce
    runs as a single donated XLA computation on the participating devices."""

    def __init__(self):
        super().__init__("tpu")

    def _reduce(self, vals):
        if len(vals) == 1:
            return vals[0]
        import jax
        import jax.numpy as jnp
        # single fused computation: stack shards host-free via device transfer
        # then tree-sum on the lead device; XLA schedules ICI transfers
        dev = vals[0].context.jax_device
        parts = [jax.device_put(v._data, dev) for v in vals]
        acc = parts[0]
        for p in parts[1:]:
            acc = acc + p
        return NDArray(acc, ctx=vals[0].context)


def _normalize(key, value):
    if isinstance(key, (int, str)):
        keys = [key]
        values = [value if isinstance(value, NDArray) else value]
    else:
        keys = list(key)
        values = list(value)
    return keys, values


def _normalize_push(key, value):
    """Returns keys + list-of-lists of arrays."""
    if isinstance(key, (int, str)):
        if isinstance(value, NDArray):
            return [key], [[value]]
        if isinstance(value, (list, tuple)) and value and isinstance(
                value[0], NDArray):
            return [key], [list(value)]
        raise MXNetError("invalid push/pull value")
    keys = list(key)
    out = []
    for v in value:
        if isinstance(v, NDArray):
            out.append([v])
        else:
            out.append(list(v))
    return keys, out


def create(name="local"):
    """Factory (reference `src/kvstore/kvstore.cc:48-64` type dispatch)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    if name == "tpu":
        return KVStoreTPU()
    if name in ("local", "local_allreduce_cpu", "local_allreduce_device",
                "device", "nccl"):
        return KVStore("device" if name.endswith("device") or
                       name in ("device", "nccl") else "local")
    if name in ("dist_sync", "dist_async", "dist_device_sync", "dist"):
        store = KVStore(name)
        return store
    raise MXNetError(f"Unknown KVStore type {name}")
