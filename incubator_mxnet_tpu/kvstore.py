"""KVStore: data-parallel parameter synchronization.

Re-expression of `src/kvstore/` (reference: `kvstore_local.h` CPU reduce,
`comm.h` CommCPU/CommDevice P2P reduce, `kvstore_nccl.h`, ps-lite
`kvstore_dist.h`) for TPU.  The API (Init/Push/Pull/set_updater/
set_optimizer, `kvstore.py` python surface) is preserved; the transport
changes per the BASELINE north star:

* ``local``  — reduce on host (CommCPU analogue)
* ``device``/``nccl`` — reduce on the accelerator (CommDevice/NCCL analogue)
* ``tpu``   — reduce as an XLA `psum` over the ICI device mesh: pushed
  per-device shards are donated to one fused all-reduce computation
  (replaces NCCL rings / PCIe spanning trees — `gpu_topology.h` is subsumed
  by XLA's collective scheduling on the torus)
* ``dist_sync``/``dist_async``/``dist_device_sync`` — multi-host via
  `jax.distributed` when initialized (each host reduces its local devices,
  then a global collective); in single-process runs they behave as ``device``
  with dist bookkeeping (rank/num_workers), which is exactly how the
  reference's nightly tests run multi-worker on localhost.

Gradient compression (reference `gradient_compression.h:52-134` 2-bit with
error feedback) is implemented in the push path with per-key residuals.
"""
from __future__ import annotations

import pickle

import numpy as _np

from .base import MXNetError
from .context import Context, cpu, tpu, num_gpus
from .ndarray.ndarray import NDArray
from . import optimizer as opt

__all__ = ["KVStore", "create"]


def _key(k):
    return str(k)


class KVStore:
    """Single-process key-value store (reference `include/mxnet/kvstore.h:59-310`)."""

    def __init__(self, kind="local"):
        self._kind = kind
        self._store = {}        # key -> NDArray (on store device)
        self._updater = None
        self._optimizer = None
        self._compression = None
        self._residuals = {}
        if kind in ("device", "nccl", "tpu") and num_gpus() > 0:
            self._store_ctx = tpu(0)
        else:
            self._store_ctx = cpu(0)

    # -- identity ------------------------------------------------------------
    @property
    def type(self):
        return self._kind

    @property
    def rank(self):
        return self._dist_rank() if "dist" in self._kind else 0

    @property
    def num_workers(self):
        return self._dist_size() if "dist" in self._kind else 1

    @staticmethod
    def _dist_rank():
        import jax
        try:
            return jax.process_index()
        except Exception:
            return 0

    @staticmethod
    def _dist_size():
        import jax
        try:
            return jax.process_count()
        except Exception:
            return 1

    # -- init/push/pull --------------------------------------------------------
    def init(self, key, value):
        """Reference `kvstore.py init`."""
        keys, values = _normalize(key, value)
        for k, v in zip(keys, values):
            if _key(k) in self._store:
                raise MXNetError(f"Key {k} already initialized")
            self._store[_key(k)] = v.copyto(self._store_ctx)

    def _commit(self, k, merged):
        """Apply a reduced value to the store: updater when installed,
        else overwrite (shared by per-key and batched push paths)."""
        sk = _key(k)
        if self._updater is not None:
            self._updater(_updater_key(k), merged, self._store[sk])
        else:
            self._store[sk]._set_data(
                merged.copyto(self._store_ctx)._data.astype(
                    self._store[sk].dtype))

    def push(self, key, value, priority=0):
        """Push values; multi-device lists are reduced (summed) first
        (reference `kvstore_local.h:184 PushImpl` → `comm.h Reduce`)."""
        keys, values = _normalize_push(key, value)
        for k, vals in zip(keys, values):
            sk = _key(k)
            if sk not in self._store:
                raise MXNetError(f"Key {k} has not been initialized")
            merged = self._reduce(vals)
            if self._compression is not None:
                merged = self._compress(sk, merged)
            self._commit(k, merged)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """Broadcast stored value to out arrays (reference `comm.h:209 Broadcast`)."""
        if out is None:
            raise MXNetError("pull requires out=")
        keys, outs = _normalize_push(key, out)
        for k, tgt_list in zip(keys, outs):
            sk = _key(k)
            if sk not in self._store:
                raise MXNetError(f"Key {k} has not been initialized")
            src = self._store[sk]
            for tgt in tgt_list:
                src.copyto(tgt)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows in row_ids (reference `PullRowSparse`,
        `kvstore.py:314`).  Host-side gather (sparse is host-resident, see
        ndarray/sparse.py design note)."""
        if out is None or row_ids is None:
            raise MXNetError("row_sparse_pull requires out= and row_ids=")
        keys, outs = _normalize_push(key, out)
        if isinstance(row_ids, NDArray):
            row_ids = [row_ids]
        for k, tgt_list in zip(keys, outs):
            src = self._store[_key(k)]
            for tgt, rids in zip(tgt_list, row_ids * len(tgt_list)):
                rows = rids.asnumpy().astype("int64")
                vals = src.asnumpy()[rows]
                from .ndarray.sparse import RowSparseNDArray
                if isinstance(tgt, RowSparseNDArray):
                    tgt._np_data = vals
                    tgt._np_indices = rows
                else:
                    full = _np.zeros(src.shape, vals.dtype)
                    full[rows] = vals
                    tgt._set_data(tgt._data * 0 + full)

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out=out, priority=priority)

    # -- reduction backends -----------------------------------------------------
    def _reduce(self, vals):
        if len(vals) == 1:
            return vals[0]
        import jax
        import jax.numpy as jnp
        if self._kind == "local":
            dev = cpu(0).jax_device
        else:
            dev = vals[0].context.jax_device
        acc = jax.device_put(vals[0]._data, dev)
        for v in vals[1:]:
            acc = acc + jax.device_put(v._data, dev)
        return NDArray(acc, ctx=vals[0].context if self._kind != "local" else cpu(0))

    # -- gradient compression ----------------------------------------------------
    def set_gradient_compression(self, compression_params):
        """2-bit compression with error feedback (reference
        `gradient_compression.h:52-134`)."""
        ctype = compression_params.get("type", "2bit")
        if ctype != "2bit":
            raise MXNetError("only 2bit gradient compression is supported "
                             "(as the reference)")
        self._compression = {
            "type": ctype,
            "threshold": float(compression_params.get("threshold", 0.5)),
        }

    def _compress(self, sk, merged):
        import jax.numpy as jnp
        thr = self._compression["threshold"]
        resid = self._residuals.get(sk)
        g = merged._data
        if resid is not None:
            g = g + resid
        q = jnp.where(g >= thr, thr, jnp.where(g <= -thr, -thr, 0.0)).astype(g.dtype)
        self._residuals[sk] = g - q
        return NDArray(q, ctx=merged.context)

    # -- optimizer integration ----------------------------------------------------
    def set_updater(self, updater):
        self._updater = updater

    _set_updater = set_updater

    def set_optimizer(self, optimizer):
        """Reference `kvstore.py set_optimizer`: in dist mode the reference
        pickles the optimizer to the servers; here the updater runs in-process
        on the reducing device."""
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    # -- server-state (de)serialization parity ------------------------------------
    def get_optimizer_states(self, dump_optimizer=False):
        """Optimizer slots as one bytes blob (the checkpoint plane's
        capture point; `dist/kvstore_dist.py` overrides to pull state back
        from the parameter servers)."""
        if self._updater is None:
            raise MXNetError("Cannot save states for distributed training")
        return self._updater.get_states(dump_optimizer)

    def set_optimizer_states(self, blob):
        if self._updater is None:
            raise MXNetError("Cannot load states for distributed training")
        self._updater.set_states(blob)

    def save_optimizer_states(self, fname, dump_optimizer=False):
        with open(fname, "wb") as f:
            f.write(self.get_optimizer_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        with open(fname, "rb") as f:
            self.set_optimizer_states(f.read())

    def _barrier(self):
        """Single-process stores have nothing to synchronize: engine order
        already serializes per-buffer access (WaitToRead semantics).  The
        distributed subclasses override this with a REAL rendezvous
        (`dist/kvstore_dist.py`); a single-process store is never a valid
        stand-in for one — assert loudly if someone treats it as such."""
        if self.num_workers != 1:
            raise MXNetError(
                f"{type(self).__name__} reports num_workers="
                f"{self.num_workers} but has no distributed barrier — use "
                "kv.create('dist_sync'/'dist_async')")

    def _send_command_to_servers(self, head, body):
        """No server processes exist for single-process stores; commands
        are meaningful only on the dist subclasses (which override)."""
        if self.num_workers != 1:
            raise MXNetError(
                "no servers to command on a single-process kvstore")


def _updater_key(k):
    return int(k) if isinstance(k, int) or (isinstance(k, str) and
                                            k.isdigit()) else k


class KVStoreTPU(KVStore):
    """`kvstore='tpu'` — push/pull as one fused all-reduce over the device
    mesh (BASELINE north star; replaces `comm.h:451 CommDevice` /
    `kvstore_nccl.h:285-402`).

    Push: the per-device gradient shards are assembled into one global
    `jax.Array` sharded over a mesh of the participating devices, and a
    cached jitted `shard_map(psum)` performs a single XLA all-reduce over
    the ICI links — no host staging, no lead-device funnel.

    Pull: the stored value is broadcast with one `device_put` onto a
    replicated `NamedSharding` over the same mesh (XLA's broadcast
    collective), and each target takes its local shard — again a single
    collective rather than N point-to-point copies.
    """

    def __init__(self, kind="tpu"):
        super().__init__(kind)
        self._meshes = {}        # tuple(device ids) -> Mesh
        self._allreduce_jit = {}  # tuple(device ids) -> jitted shard_map psum
        # last mesh a key was pushed over; lets pull() reuse the same devices
        self._key_mesh = {}
        self._concat_jit = None  # lazy shared flatten+concat program
        self._split_jit = {}     # (device ids, shapes) -> split program
        self.allreduce_dispatches = 0   # tests assert one per step

    def _mesh_for(self, devices):
        ids = tuple(d.id for d in devices)
        mesh = self._meshes.get(ids)
        if mesh is None:
            import numpy as np
            from jax.sharding import Mesh
            mesh = Mesh(np.asarray(devices), ("dev",))
            self._meshes[ids] = mesh
        return mesh

    def _allreduce(self, mesh):
        """One jitted all-reduce over the mesh: (N, *s) sharded on 'dev'
        → summed (*s), replicated on every participating device."""
        ids = tuple(d.id for d in mesh.devices.flat)
        fn = self._allreduce_jit.get(ids)
        if fn is None:
            import jax
            from jax.sharding import PartitionSpec as P
            shard_map = getattr(jax, "shard_map", None)
            if shard_map is None:                     # older jax
                from jax.experimental.shard_map import shard_map

            def _psum(shards):           # shards: (1, *s) local block
                return jax.lax.psum(shards[0], "dev")

            fn = jax.jit(shard_map(_psum, mesh=mesh,
                                   in_specs=P("dev"), out_specs=P()))
            self._allreduce_jit[ids] = fn
        return fn

    def _reduce(self, vals):
        if len(vals) == 1:
            return vals[0]
        import jax
        devices = [v.context.jax_device for v in vals]
        if len({d.id for d in devices}) != len(devices):
            # duplicate devices (e.g. all values on one chip): plain sum
            acc = vals[0]._data
            for v in vals[1:]:
                acc = acc + jax.device_put(v._data, devices[0])
            return NDArray(acc, ctx=vals[0].context)
        mesh = self._mesh_for(devices)
        shape = tuple(vals[0].shape)
        return NDArray(
            self._mesh_allreduce(mesh, shape,
                                 [v._data for v in vals],
                                 vals[0].context.jax_device.id),
            ctx=vals[0].context)

    def _mesh_allreduce(self, mesh, shape, shards, lead_id):
        """Assemble per-device shards into one mesh array, psum with ONE
        collective, return the lead device's replicated shard (downstream
        single-device math sees an ordinary committed array; the pull path
        re-broadcasts with one collective)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        global_arr = jax.make_array_from_single_device_arrays(
            (len(shards),) + shape, NamedSharding(mesh, P("dev")),
            [b.reshape((1,) + shape) for b in shards])
        self.allreduce_dispatches += 1
        summed = self._allreduce(mesh)(global_arr)
        return next(s.data for s in summed.addressable_shards
                    if s.device.id == lead_id)

    def _record_key_mesh(self, sk, vals):
        """Remember the device set a key was pushed over so pull() can use
        the one-collective broadcast instead of per-target copies."""
        if len(vals) > 1:
            devs = [v.context.jax_device for v in vals]
            if len({d.id for d in devs}) == len(devs):
                self._key_mesh[sk] = self._mesh_for(devs)

    @property
    def prefers_batched_push(self):
        """Multi-key push/pull should arrive as one call: the whole key
        list reduces with ONE collective (`_reduce_many`) instead of one
        per parameter (the reference's batched NCCL push, `model.py:125`)."""
        return True

    def _reduce_many(self, values):
        """Bucketed multi-key reduce: per device, flatten+concat every
        key's local shard (one program per device), ONE psum over the
        bucket, split the lead shard back.  ~ndev+2 dispatches per step
        instead of 2 per key."""
        import jax
        import jax.numpy as jnp

        first_devs = [v.context.jax_device for v in values[0]]
        ids0 = tuple(d.id for d in first_devs)
        same = all(
            tuple(v.context.jax_device.id for v in vals) == ids0
            and vals[0].dtype == values[0][0].dtype
            for vals in values)
        if not same or len(first_devs) == 1 or \
                len(set(ids0)) != len(ids0):
            return [self._reduce(vals) for vals in values]

        shapes = [tuple(vals[0].shape) for vals in values]
        sizes = [int(_np.prod(s)) if s else 1 for s in shapes]
        offs = _np.cumsum([0] + sizes)
        total = int(offs[-1])
        mesh = self._mesh_for(first_devs)

        if self._concat_jit is None:
            # one shape-agnostic program: jit's own cache specializes per
            # input signature
            self._concat_jit = jax.jit(lambda *xs: jnp.concatenate(
                [x.reshape(-1) for x in xs]))
        cat = self._concat_jit
        per_dev = []
        for d in range(len(first_devs)):
            per_dev.append(cat(*[vals[d]._data for vals in values]))
        local = self._mesh_allreduce(mesh, (total,), per_dev,
                                     first_devs[0].id)
        split = self._split_jit.get((ids0, tuple(shapes)))
        if split is None:
            def _split(buf, shapes=shapes, offs=offs):
                return tuple(
                    jax.lax.dynamic_slice_in_dim(
                        buf, int(offs[k]), sizes[k]).reshape(shapes[k])
                    for k in range(len(shapes)))
            split = jax.jit(_split)
            self._split_jit[(ids0, tuple(shapes))] = split
        pieces = split(local)
        ctx0 = values[0][0].context
        return [NDArray(p, ctx=ctx0) for p in pieces]

    def push(self, key, value, priority=0):
        keys, values = _normalize_push(key, value)
        for k, vals in zip(keys, values):
            self._record_key_mesh(_key(k), vals)
        if len(keys) > 1 and self._compression is None and \
                all(len(vals) > 1 for vals in values):
            for k in keys:
                if _key(k) not in self._store:
                    raise MXNetError(f"Key {k} has not been initialized")
            merged = self._reduce_many(values)
            for k, m in zip(keys, merged):
                self._commit(k, m)
            return
        super().push(key, value, priority)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        if out is None:
            raise MXNetError("pull requires out=")
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        keys, outs = _normalize_push(key, out)
        for k, tgt_list in zip(keys, outs):
            sk = _key(k)
            if sk not in self._store:
                raise MXNetError(f"Key {k} has not been initialized")
            src = self._store[sk]
            mesh = self._key_mesh.get(sk)
            tgt_devs = {t.context.jax_device.id for t in tgt_list}
            mesh_devs = ({d.id for d in mesh.devices.flat}
                         if mesh is not None else set())
            if mesh is not None and len(tgt_list) > 1 and \
                    tgt_devs <= mesh_devs:
                # one broadcast collective over the mesh, then local shards
                rep = jax.device_put(src._data, NamedSharding(mesh, P()))
                by_dev = {s.device.id: s.data for s in rep.addressable_shards}
                for tgt in tgt_list:
                    tgt._set_data(by_dev[tgt.context.jax_device.id]
                                  .astype(tgt.dtype))
            else:
                for tgt in tgt_list:
                    src.copyto(tgt)


def _normalize(key, value):
    if isinstance(key, (int, str)):
        keys = [key]
        values = [value if isinstance(value, NDArray) else value]
    else:
        keys = list(key)
        values = list(value)
    return keys, values


def _normalize_push(key, value):
    """Returns keys + list-of-lists of arrays."""
    if isinstance(key, (int, str)):
        if isinstance(value, NDArray):
            return [key], [[value]]
        if isinstance(value, (list, tuple)) and value and isinstance(
                value[0], NDArray):
            return [key], [list(value)]
        raise MXNetError("invalid push/pull value")
    keys = list(key)
    out = []
    for v in value:
        if isinstance(v, NDArray):
            out.append([v])
        else:
            out.append(list(v))
    return keys, out


def create(name="local"):
    """Factory (reference `src/kvstore/kvstore.cc:48-64` type dispatch)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    if name == "tpu":
        return KVStoreTPU()
    if name in ("device", "nccl", "local_allreduce_device"):
        # device-side reduce: same single-collective engine as 'tpu'
        # (reference comm.h CommDevice / kvstore_nccl.h both lower to one
        # all-reduce; so do we)
        return KVStoreTPU("device")
    if name in ("local", "local_allreduce_cpu"):
        return KVStore("local")
    if name in ("dist_sync", "dist_async", "dist_device_sync",
                "dist_sync_device", "dist"):
        import os
        role = os.environ.get("DMLC_ROLE")
        if role == "server":
            # the reference runs the same user script on server hosts; the
            # process becomes the server and never returns to user code
            # (python/mxnet/kvstore_server.py _init_kvstore_server_module).
            # Constraint vs the reference: one server, colocated with the
            # root URI host (gradient traffic rides the TPU mesh, the
            # server is control-plane only).
            import sys
            from .dist.server import ParameterServer
            ParameterServer(
                host=os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1"),
                port=int(os.environ.get("DMLC_PS_ROOT_PORT", 9091)),
            ).serve_forever()
            sys.exit(0)
        if role == "scheduler":
            # no scheduler in this architecture (no rendezvous needed: the
            # single server's address is static); exit cleanly so external
            # trackers that spawn one are satisfied
            import sys
            sys.exit(0)
        if os.environ.get("DMLC_PS_ROOT_URI") or role == "worker":
            from .dist.kvstore_dist import KVStoreDist
            return KVStoreDist(name)
        # no tracker env: single-process stand-in with dist bookkeeping
        # (how the reference's unit tests run dist kvstores too)
        return KVStore(name)
    raise MXNetError(f"Unknown KVStore type {name}")
