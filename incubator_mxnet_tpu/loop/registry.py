"""Versioned model registry — the train-to-serve hand-off directory.

The trainer's `CheckpointPublisher` (publisher.py) writes one manifest
per published version into a shared directory; the serving fleet's
`LoopController` (controller.py) polls the same directory.  Three rules
make the hand-off safe across processes and hosts that share nothing but
this directory:

* a version manifest is written temp-file + ``os.replace`` — readers see
  either the whole manifest or none of it; any file that does not parse
  as a stamped ``incubator_mxnet_tpu.registry/1`` record is INVISIBLE
  (counted, never surfaced), so a torn publish can never be picked up;
* a ``rejected`` stamp is a sidecar file, not a manifest edit — stamping
  is idempotent (first stamp wins), survives process restart, and hides
  the version from every reader from then on, so a canary-rejected
  version is never retried;
* a ``fence`` record hides a whole step window — the trainer writes one
  when the guardian rolls back or training diverges, so versions
  published from a contaminated window disappear from readers even if
  their manifests landed before the anomaly was detected.

Registry layout (all JSON, all atomic)::

    registry/
      v-0000000120.json           # version manifest (version == step)
      v-0000000120.rejected.json  # canary-rejection stamp (sidecar)
      fence-0000000121-0000000160.json   # contaminated window [lo, hi]
      blobs/v-0000000120/         # pinned checkpoint (publish(pin=True))

A missing registry root raises a structured `RegistryUnavailableError`
rather than returning "no versions": the watcher must distinguish "no
new model yet" (keep polling) from "storage is gone" (keep serving the
incumbent and alarm).
"""
from __future__ import annotations

import json
import os
import re
import shutil
import time

from ..base import MXNetError
from ..checkpoint.manifest import atomic_write_json
from ..resilience import faults as _faults

REGISTRY_FORMAT = "incubator_mxnet_tpu.registry/1"
_VERSION_RE = re.compile(r"^v-(\d+)\.json$")
_REJECT_RE = re.compile(r"^v-(\d+)\.rejected\.json$")
_FENCE_RE = re.compile(r"^fence-(\d+)-(\d+)\.json$")


class RegistryUnavailableError(MXNetError):
    """The registry directory is gone or unreadable mid-poll.

    Carries ``root`` so the watcher can alarm on the exact path; the
    correct response on the serving side is to keep the incumbent live
    and retry on the next poll, never to tear anything down.
    """

    def __init__(self, root, detail=""):
        self.root = root
        super().__init__(
            f"model registry unavailable at '{root}'"
            + (f": {detail}" if detail else ""))


def _version_name(version):
    return "v-%010d.json" % int(version)


def _reject_name(version):
    return "v-%010d.rejected.json" % int(version)


def _fence_name(lo, hi):
    return "fence-%010d-%010d.json" % (int(lo), int(hi))


class ModelRegistry:
    """Reader/writer for one registry directory.

    Stateless between calls — every read re-lists the directory, so
    multiple processes (trainer, N serving hosts) can share one root
    with no coordination beyond the filesystem's atomic rename.
    """

    def __init__(self, root, create=True):
        self.root = str(root)
        if create:
            os.makedirs(self.root, exist_ok=True)
        self._torn_seen = 0

    # ------------------------------------------------------------- write
    def publish(self, checkpoint, *, step, health=None, watermark=None,
                score=None, meta=None, pin=False):
        """Publish one version (version number == trained step).

        With ``pin=True`` the checkpoint directory is first hardlinked
        (copy fallback) into ``registry/blobs/`` and the version record
        points at that registry-owned copy — the published weights then
        outlive the trainer's own checkpoint retention, which prunes
        old ``ckpt-*`` directories on its own schedule.

        Fires the ``publish.commit`` fault site; a ``torn`` clause there
        emulates the publisher dying mid-rename by leaving a TRUNCATED
        manifest under the final name — the exact garbage readers must
        treat as invisible — and re-raises `TornWrite` so the caller
        knows the publish did not commit.
        """
        self._require_root()
        source = str(checkpoint)
        if pin:
            checkpoint = self._pin_checkpoint(checkpoint, step)
        rec = {
            "format": REGISTRY_FORMAT,
            "version": int(step),
            "step": int(step),
            "checkpoint": str(checkpoint),
            # the trainer-side directory the pin was taken from: a
            # canary rejection must stamp THAT path too, or resume /
            # replica boot scanning the trainer's checkpoint_dir (not
            # the registry blobs/) would never see the verdict
            "source_checkpoint": source,
            "health": dict(health or {}),
            "watermark": dict(watermark or {}),
            "score": score,
            "meta": dict(meta or {}),
            "published_unix": time.time(),
        }
        path = os.path.join(self.root, _version_name(step))
        try:
            _faults.fire("publish.commit", version=int(step))
        except _faults.TornWrite:
            blob = json.dumps(rec, indent=1, sort_keys=True)
            with open(path, "w") as f:
                f.write(blob[:max(1, len(blob) // 2)])
            raise
        atomic_write_json(path, rec)
        return rec

    def reject(self, version, reason="", **info):
        """Stamp `version` rejected — idempotent, first stamp wins.

        The stamp is a sidecar file so it survives a re-publish of the
        same version (the manifest may be atomically replaced; the stamp
        stays) and a process restart (it is on disk, not in memory).
        """
        self._require_root()
        path = os.path.join(self.root, _reject_name(version))
        existing = self._read_json(path)
        if existing is not None:
            return existing
        rec = {"version": int(version), "rejected": True,
               "reason": str(reason), "rejected_unix": time.time()}
        rec.update(info)
        atomic_write_json(path, rec)
        return rec

    def fence(self, lo_step, hi_step, reason=""):
        """Hide every version with lo_step <= version <= hi_step.

        Written by the trainer when the guardian rolls back (the window
        between the last good step and the detected anomaly trained on
        data it has now disowned) or when training diverges outright.
        """
        self._require_root()
        lo, hi = int(lo_step), int(hi_step)
        if hi < lo:
            lo, hi = hi, lo
        rec = {"lo": lo, "hi": hi, "reason": str(reason),
               "fenced_unix": time.time()}
        atomic_write_json(os.path.join(self.root, _fence_name(lo, hi)), rec)
        return rec

    # -------------------------------------------------------------- read
    def versions(self, include_rejected=False, include_fenced=False):
        """Sorted (oldest first) list of visible version records.

        Each record is annotated with ``rejected``/``fenced`` booleans;
        torn or unstamped manifests are never surfaced (counted in
        `stats()["torn_manifests"]`).
        """
        names = self._listdir()
        rejected = set()
        for name in names:
            m = _REJECT_RE.match(name)
            if m:
                rejected.add(int(m.group(1)))
        fences = self._fences(names)
        out, torn = [], 0
        for name in names:
            m = _VERSION_RE.match(name)
            if not m:
                continue
            rec = self._read_json(os.path.join(self.root, name))
            if (rec is None or rec.get("format") != REGISTRY_FORMAT
                    or not isinstance(rec.get("version"), int)):
                torn += 1
                continue
            v = rec["version"]
            rec = dict(rec)
            rec["rejected"] = v in rejected
            rec["fenced"] = any(lo <= v <= hi for lo, hi in fences)
            if rec["rejected"] and not include_rejected:
                continue
            if rec["fenced"] and not include_fenced:
                continue
            out.append(rec)
        self._torn_seen = torn
        out.sort(key=lambda r: r["version"])
        return out

    def latest(self, **kw):
        """Newest visible (not rejected, not fenced, not torn) version."""
        recs = self.versions(**kw)
        return recs[-1] if recs else None

    def get(self, version):
        """The visible record for `version`, or None."""
        for rec in self.versions(include_rejected=True, include_fenced=True):
            if rec["version"] == int(version):
                return rec
        return None

    def rejected(self, version):
        """The rejection stamp for `version`, or None."""
        if not os.path.isdir(self.root):
            raise RegistryUnavailableError(self.root)
        return self._read_json(
            os.path.join(self.root, _reject_name(version)))

    def fenced(self, version):
        """Whether `version` falls inside any fence window."""
        return any(lo <= int(version) <= hi
                   for lo, hi in self._fences(self._listdir()))

    def fences(self):
        """Sorted [(lo, hi)] fence windows."""
        return self._fences(self._listdir())

    # surfaced through the 'loop' / 'loop.publisher' producers — a
    # registry is a stateless per-call reader, often several per
    # process, so it has no stable namespace of its own
    def stats(self):   # mxlint: disable=untracked-stats
        try:
            recs = self.versions(include_rejected=True, include_fenced=True)
        except RegistryUnavailableError:
            return {"available": 0}
        visible = [r for r in recs if not r["rejected"] and not r["fenced"]]
        return {
            "available": 1,
            "versions": len(recs),
            "visible": len(visible),
            "rejected": sum(r["rejected"] for r in recs),
            "fenced": sum(r["fenced"] for r in recs),
            "torn_manifests": self._torn_seen,
            "latest_version": visible[-1]["version"] if visible else -1,
        }

    # --------------------------------------------------------- internals
    def _pin_checkpoint(self, src, step):
        """Hardlink (copy fallback) `src` into ``blobs/v-<step>/``.

        Published versions must outlive the trainer's own checkpoint
        retention (fit prunes old ``ckpt-*`` dirs); pinning gives the
        registry its own reference.  Idempotent: an existing pin wins,
        including against a concurrent publisher racing the rename.
        """
        dst = os.path.join(self.root, "blobs", "v-%010d" % int(step))
        if os.path.isdir(dst):
            return dst
        tmp = dst + ".tmp.%d" % os.getpid()
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        for name in sorted(os.listdir(src)):
            s, d = os.path.join(src, name), os.path.join(tmp, name)
            if os.path.isdir(s):
                shutil.copytree(s, d)
                continue
            try:
                os.link(s, d)
            except OSError:
                shutil.copy2(s, d)
        try:
            os.rename(tmp, dst)
        except OSError:
            # a concurrent publisher pinned the same version first
            shutil.rmtree(tmp, ignore_errors=True)
        return dst

    def _require_root(self):
        if not os.path.isdir(self.root):
            raise RegistryUnavailableError(self.root)

    def _listdir(self):
        try:
            return os.listdir(self.root)
        except OSError as e:
            raise RegistryUnavailableError(self.root, str(e)) from e

    def _fences(self, names):
        out = []
        for name in names:
            m = _FENCE_RE.match(name)
            if m:
                out.append((int(m.group(1)), int(m.group(2))))
        out.sort()
        return out

    @staticmethod
    def _read_json(path):
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None
