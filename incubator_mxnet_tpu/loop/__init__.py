"""Continuous train-to-serve loop (the closed production loop).

Composes the subsystems that already exist — elastic checkpoints with
guardian health stamps, the replica router's zero-compile rolling swap,
the obs plane — into the loop production actually runs:

* `ModelRegistry` (registry.py) — the versioned, atomic hand-off
  directory between trainer and fleet; torn manifests invisible,
  ``rejected`` stamps and guardian ``fence`` windows hide versions
  permanently;
* `CheckpointPublisher` (publisher.py) — rides `Module.fit`, publishes
  guardian-healthy checkpoints on a cadence with a data-shard watermark
  and fences rollback/divergence windows out of the registry;
* `LoopController` (controller.py) — serving-side watcher: every new
  version is canaried on ONE replica against a pinned holdout before
  the rolling swap promotes it; failed canaries are swapped back,
  stamped rejected, and surfaced as `CanaryRejectedError`.

Freshness is measured end-to-end as ``loop.freshness_lag_s`` (data-seen
watermark → serving-live) and gated in LOOP_REPORT.json
(tools/run_loop_gate.py); the adversarial composition — poisoned shard,
torn publish, failed canary, vanished registry — is certified by
``tools/run_chaos.py --loop`` (CHAOS_LOOP.json).
"""
from __future__ import annotations

from .registry import (ModelRegistry, RegistryUnavailableError,
                       REGISTRY_FORMAT)
from .publisher import CheckpointPublisher
from .controller import CanaryRejectedError, LoopController

__all__ = ["ModelRegistry", "RegistryUnavailableError", "REGISTRY_FORMAT",
           "CheckpointPublisher", "LoopController", "CanaryRejectedError"]
