"""Trainer-side publisher: elastic checkpoints → registry versions.

`CheckpointPublisher` rides `Module.fit` as a batch-end callback.  On a
cadence (``MXNET_LOOP_PUBLISH_STEPS`` trained steps and/or
``MXNET_LOOP_PUBLISH_SECS`` wall-clock) it takes the newest
guardian-healthy elastic checkpoint and publishes it into a
`ModelRegistry` with:

* the guardian's health stamp copied from the checkpoint manifest —
  suspect snapshots are never published (fit's snapshot path already
  refuses to stamp one healthy mid-anomaly; the publisher re-filters via
  `latest_healthy` anyway, belt and braces);
* a data-shard WATERMARK — the max record position the snapshot's
  training had consumed, plus the wall-clock time the snapshot
  committed.  ``loop.freshness_lag_s`` on the serving side is measured
  against this time: data seen → model live;
* an optional holdout score from ``score_fn(checkpoint_path)`` —
  advisory on the trainer side; the serving canary re-scores on its own
  pinned holdout and trusts only that.

Guardian composition: when fit hands the callback a guardian (it is in
``BatchEndParam.locals``), the publisher watches its rollback counter
and fences the exact window the guardian disowned
(``guardian.last_rollback_window``) — this catches rollbacks that
resume at the very step they had reached and so show no callback-visible
regression.  Without a guardian handle, a step REGRESSION across
callbacks is the fallback signal: every version in the disowned window
``(step_now, max_step_seen]`` trained on quarantined data, so the
publisher fences that window out of the registry.
`fit()` (the wrapper entry point) additionally converts a
`TrainingDivergedError` escape into a fence from the last good step
before re-raising — divergence means nothing after the last rollback
point can be trusted.
"""
from __future__ import annotations

import logging
import os
import time

from .. import config as _config
from ..base import MXNetError
from ..checkpoint import manifest as _manifest
from ..obs import metrics as _metrics
from ..resilience import faults as _faults
from ..resilience.guardian import TrainingDivergedError
from .registry import ModelRegistry

_LOG = logging.getLogger(__name__)


class CheckpointPublisher:
    """Publish guardian-healthy checkpoints into a registry on a cadence.

    Use either as a plain batch-end callback on an existing ``fit``::

        pub = CheckpointPublisher(registry, ckpt_dir)
        mod.fit(it, ..., checkpoint_dir=ckpt_dir, batch_end_callback=pub)

    or via the wrapper, which also fences the registry when training
    diverges::

        pub.fit(mod, it, num_epoch=4, checkpoint_dir=ckpt_dir, ...)
    """

    def __init__(self, registry, checkpoint_dir, publish_steps=None,
                 publish_secs=None, score_fn=None):
        self.registry = (registry if isinstance(registry, ModelRegistry)
                         else ModelRegistry(registry))
        self.checkpoint_dir = str(checkpoint_dir)
        self.publish_steps = int(
            _config.get("MXNET_LOOP_PUBLISH_STEPS")
            if publish_steps is None else publish_steps)
        self.publish_secs = float(
            _config.get("MXNET_LOOP_PUBLISH_SECS")
            if publish_secs is None else publish_secs)
        self.score_fn = score_fn
        self._last_pub_step = -1      # step of the newest published version
        self._cadence_anchor = -1     # step the step-cadence counts from
        self._last_pub_time = time.time()
        self._max_step_seen = -1
        self._published = 0
        self._publish_failures = 0
        self._torn_publishes = 0
        self._fences = 0
        self._rollbacks_seen = 0
        _metrics.register_producer("loop.publisher", self.stats)

    # ------------------------------------------------------ fit plumbing
    def __call__(self, param):
        """Batch-end callback: cadence check + rollback-fence watch."""
        loc = getattr(param, "locals", None) or {}
        step = loc.get("gstep")
        if step is None:
            step = self._max_step_seen + 1
        train_data = loc.get("train_data")
        self.poll(int(step), train_data=train_data,
                  guardian=loc.get("guardian"))

    def fit(self, module, train_data, **kwargs):
        """`module.fit(train_data, ...)` with this publisher attached.

        A `TrainingDivergedError` escaping fit fences everything after
        the guardian's last good step out of the registry, then
        re-raises — the trainer is dead, the registry must not keep
        offering its contaminated tail to the fleet.
        """
        cbs = kwargs.pop("batch_end_callback", None)
        cbs = list(cbs) if isinstance(cbs, (list, tuple)) \
            else ([cbs] if cbs is not None else [])
        cbs.append(self)
        kwargs.setdefault("checkpoint_dir", self.checkpoint_dir)
        try:
            return module.fit(train_data, batch_end_callback=cbs, **kwargs)
        except TrainingDivergedError:
            lo = self._last_good_step(module) + 1
            self.fence_window(lo, max(self._max_step_seen, lo),
                              reason="training-diverged")
            raise

    # ----------------------------------------------------------- cadence
    def poll(self, step, train_data=None, guardian=None):
        """One cadence tick at trained step `step` (idempotent, cheap)."""
        step = int(step)
        if guardian is not None:
            # the authoritative rollback signal: the guardian's own
            # counter.  A rollback that resumes at exactly the step it
            # had reached shows NO step regression at the callbacks, but
            # the window (last_good, max_seen] is still disowned.
            rb = getattr(guardian, "_rollbacks", 0)
            if rb > self._rollbacks_seen:
                self._rollbacks_seen = rb
                win = getattr(guardian, "last_rollback_window", None)
                if win is not None:
                    lo, hi = int(win[0]), int(win[1])
                else:
                    lo = int(getattr(guardian, "_last_good_step",
                                     step)) + 1
                    hi = max(self._max_step_seen, step)
                self.fence_window(lo, max(hi, lo),
                                  reason="guardian-rollback")
                self._cadence_anchor = min(self._cadence_anchor, lo - 1)
        if 0 <= step < self._max_step_seen:
            # step regression across callbacks — a rollback seen without
            # a guardian handle (plain poll() callers): fence likewise
            self.fence_window(step + 1, self._max_step_seen,
                              reason="guardian-rollback")
            self._cadence_anchor = min(self._cadence_anchor, step)
        self._max_step_seen = max(self._max_step_seen, step)
        due = False
        if self.publish_steps > 0:
            due = step - self._cadence_anchor >= self.publish_steps
        if not due and self.publish_secs > 0:
            due = time.time() - self._last_pub_time >= self.publish_secs
        if not due:
            return None
        rec = self._publish_latest(train_data)
        if rec is not None or self.publish_steps <= 0:
            self._cadence_anchor = step
        self._last_pub_time = time.time()
        return rec

    def fence_window(self, lo, hi, reason=""):
        if hi < lo:
            return None
        self._fences += 1
        _LOG.warning("publisher: fencing registry versions [%d, %d] (%s)",
                     lo, hi, reason)
        try:
            return self.registry.fence(lo, hi, reason=reason)
        except MXNetError as e:
            self._publish_failures += 1
            _LOG.error("publisher: fence write failed: %s", e)
            return None

    # ----------------------------------------------------------- publish
    def _publish_latest(self, train_data=None):
        """Publish the newest healthy, unfenced, unrejected checkpoint
        newer than the last published version; None if there is none."""
        try:
            blocked = self._blocked
            path = _manifest.latest_healthy(self.checkpoint_dir,
                                            exclude=blocked)
        except MXNetError as e:
            self._publish_failures += 1
            _LOG.error("publisher: registry unavailable: %s", e)
            return None
        if path is None:
            return None
        try:
            man = _manifest.read_manifest(path)
        except (OSError, ValueError, MXNetError):
            return None
        step = int(man.get("step", 0))
        if step <= self._last_pub_step:
            return None
        watermark = self._watermark(path, man, train_data)
        score = None
        if self.score_fn is not None:
            try:
                score = float(self.score_fn(path))
            except Exception as e:   # advisory only — never kills training
                _LOG.warning("publisher: score_fn failed: %s", e)
        health = (man.get("meta") or {}).get("health") or {}
        try:
            rec = self.registry.publish(
                path, step=step, health=health, watermark=watermark,
                score=score, pin=True)
        except _faults.TornWrite:
            self._torn_publishes += 1
            _LOG.error("publisher: torn publish of step %d (will retry "
                       "next cadence)", step)
            return None
        except MXNetError as e:
            self._publish_failures += 1
            _LOG.error("publisher: publish of step %d failed: %s", step, e)
            return None
        self._published += 1
        self._last_pub_step = step
        return rec

    def _blocked(self, step):
        """exclude= hook for latest_healthy: fenced or rejected steps."""
        try:
            return (self.registry.fenced(step)
                    or self.registry.rejected(step) is not None)
        except MXNetError:
            return False

    def _watermark(self, path, man, train_data):
        """Max record position + wall time the snapshot's data reaches."""
        wm = {
            "step": int(man.get("step", 0)),
            "epoch": int(man.get("epoch", 0)),
            "nbatch": int(man.get("nbatch", 0)),
        }
        try:
            wm["time"] = os.path.getmtime(
                os.path.join(path, _manifest.MANIFEST_NAME))
        except OSError:
            wm["time"] = time.time()
        rr = None
        if train_data is not None:
            try:
                rr = train_data.record_range(wm["nbatch"])
            except Exception:
                rr = None
        if rr is not None:
            wm["source"], wm["record_lo"], wm["record_hi"] = \
                str(rr[0]), int(rr[1]), int(rr[2])
        return wm

    @staticmethod
    def _last_good_step(module):
        g = getattr(module, "_guardian", None)
        lg = getattr(g, "_last_good_step", None)
        return int(lg) if lg is not None else 0

    # ------------------------------------------------------------- stats
    def stats(self):
        return {
            "published": self._published,
            "publish_failures": self._publish_failures,
            "torn_publishes": self._torn_publishes,
            "fences": self._fences,
            "last_published_version": self._last_pub_step,
            "max_step_seen": self._max_step_seen,
        }
