"""Serving-side watcher + canary gate.

`LoopController` closes the train-to-serve loop: it polls a
`ModelRegistry` for new versions and, instead of blind-swapping the
fleet, runs every candidate through a CANARY —

1. pick one healthy replica; score the INCUMBENT weights on a pinned
   holdout slice through the real inference path (`replica.submit`, the
   same deepcheck path health probes use);
2. swap ONLY that replica to the candidate checkpoint (the router's
   drain + zero-compile swap, scoped to one replica);
3. score the candidate on the same holdout, same replica —
   apples-to-apples, same device, same compiled programs;
4. promote iff ``canary_score >= incumbent_score - MXNET_LOOP_CANARY_TOL``
   via the existing rolling zero-compile `swap_weights` across the
   fleet; otherwise swap the canary replica BACK to the incumbent,
   stamp the version ``rejected`` in the registry (never retried), and
   raise `CanaryRejectedError` naming version and both scores.

Structured failure handling, never tear-down:

* `SwapInProgressError` from the router (another swap mid-flight) →
  back off, retry the same version on the next poll; if it is the
  canary ROLLBACK that collides with an external roll, the restore is
  deferred and retried at the next poll instead of destroying the
  replica;
* a replica LOST (or transport wedged) mid-canary/mid-promote → the
  router's swap contract keeps the fleet serving (each request is
  single-version); the controller counts a ``swap_failure``, returns a
  structured ``swap-failed`` status, and retries the whole canary on
  the next poll — never crashes the watch loop.  A promote that aborts
  AFTER the canary passed is resumed directly on the next poll (the
  verdict stands; re-canarying against a partially-rolled fleet could
  compare the candidate against itself);
* `RegistryUnavailableError` (registry directory vanished mid-poll) →
  count it, keep serving the incumbent;
* a failure scoring the INCUMBENT (before anything was swapped) is an
  eval problem, not a swap problem: counted under ``eval_failures``,
  returned as an ``eval-failed`` status, candidate retried next poll;
* a canary-eval failure (``canary.eval`` fault site, inference error,
  timeout) fails CLOSED: the candidate is treated as scoring -inf and
  rejected — a model that cannot be scored is never promoted.

On promote, the controller measures ``loop.freshness_lag_s`` — wall
clock now minus the version's data-seen watermark time — and publishes
it as an obs gauge under the ``loop`` namespace, with a trace span per
poll/canary/promote so the hand-off is visible end-to-end.
"""
from __future__ import annotations

import logging
import threading
import time

# on Python <= 3.10 this is NOT the builtin TimeoutError: a hung
# Future.result would otherwise escape every fail-closed handler below
from concurrent.futures import TimeoutError as _FutTimeout

import numpy as _np

from .. import config as _config
from ..base import MXNetError
from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..resilience import faults as _faults
from ..serving.replica import ReplicaLostError
from ..serving.router import SwapInProgressError
from .registry import ModelRegistry, RegistryUnavailableError

_LOG = logging.getLogger(__name__)


class CanaryRejectedError(MXNetError):
    """A candidate version failed the serving-side canary gate."""

    def __init__(self, version, incumbent_score, canary_score, tol=None):
        self.version = int(version)
        self.incumbent_score = incumbent_score
        self.canary_score = canary_score
        self.tol = tol
        super().__init__(
            f"canary rejected version {version}: canary scored "
            f"{canary_score} vs incumbent {incumbent_score}"
            + (f" (tol={tol})" if tol is not None else ""))


def _accuracy(outputs, labels):
    """Default holdout score: argmax accuracy of the first output."""
    out = outputs[0] if isinstance(outputs, (list, tuple)) else outputs
    out = out.asnumpy() if hasattr(out, "asnumpy") else _np.asarray(out)
    pred = out.argmax(axis=-1).reshape(-1)
    labels = _np.asarray(labels).reshape(-1)
    n = min(len(pred), len(labels))
    return float((pred[:n] == labels[:n]).mean()) if n else 0.0


class LoopController:
    """Watch a registry, canary every new version, promote or reject.

    `holdout` is ``(inputs, labels)``: inputs a dict of input-name →
    array sized to fit the fleet's bucket ladder, labels whatever
    ``score_fn(outputs, labels) -> float`` (higher is better) consumes;
    the default scorer is argmax accuracy of the first output.
    """

    def __init__(self, router, registry, holdout, score_fn=None,
                 canary_tol=None, poll_interval_s=None,
                 freshness_slo_s=None, eval_timeout_ms=30000,
                 incumbent_checkpoint=None):
        self.router = router
        # what a failed canary is restored FROM before any promotion has
        # happened: the checkpoint the fleet booted with
        self.incumbent_checkpoint = incumbent_checkpoint
        self.registry = (registry if isinstance(registry, ModelRegistry)
                         else ModelRegistry(registry, create=False))
        self.holdout_inputs, self.holdout_labels = holdout
        self.score_fn = score_fn or _accuracy
        self.canary_tol = float(
            _config.get("MXNET_LOOP_CANARY_TOL")
            if canary_tol is None else canary_tol)
        self.poll_interval_s = float(
            _config.get("MXNET_LOOP_POLL_S")
            if poll_interval_s is None else poll_interval_s)
        self.freshness_slo_s = float(
            _config.get("MXNET_LOOP_FRESHNESS_SLO_S")
            if freshness_slo_s is None else freshness_slo_s)
        self.eval_timeout_ms = int(eval_timeout_ms)
        self._live = None            # registry record of the live version
        # (version, incumbent_score, canary_score) of a candidate whose
        # canary PASSED but whose fleet-wide promote roll aborted — the
        # next poll resumes the roll instead of re-canarying (some
        # replicas already serve the candidate, so a fresh canary pick
        # could compare the candidate against itself)
        self._vetted = None
        # (rid, checkpoint) of a canary rollback deferred because an
        # external swap held the lock — retried first thing next poll
        self._pending_restore = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self._polls = 0
        self._promotions = 0
        self._rejections = 0
        self._swap_busy = 0
        self._swap_failures = 0
        self._registry_errors = 0
        self._eval_failures = 0
        self._freshness_lag_s = None
        self._gauge = _metrics.gauge("loop.freshness_lag_s")
        _metrics.register_producer("loop", self.stats)

    # -------------------------------------------------------------- poll
    def poll_once(self):
        """One watch cycle; returns a structured status dict.

        Raises `CanaryRejectedError` on a failed canary (the background
        thread records and continues; a direct caller sees it).
        """
        self._polls += 1
        sp = _trace.start_span("loop.poll", cat="loop")
        try:
            if self._pending_restore is not None:
                # a canary rollback that lost the swap lock last poll:
                # finish it before looking at anything new — the replica
                # is still serving the rejected weights
                rid, restore_ckpt = self._pending_restore
                self._pending_restore = None
                self._restore_canary(rid, incumbent_ckpt=restore_ckpt)
            try:
                cand = self.registry.latest()
            except RegistryUnavailableError as e:
                self._registry_errors += 1
                _LOG.error("loop: %s — fleet keeps serving the incumbent",
                           e)
                return {"status": "registry-unavailable", "error": str(e)}
            if cand is None:
                return {"status": "idle"}
            live_v = self._live["version"] if self._live else -1
            if cand["version"] <= live_v:
                return {"status": "idle", "live_version": live_v}
            try:
                return self._canary_and_promote(cand)
            except SwapInProgressError as e:
                self._swap_busy += 1
                _LOG.info("loop: swap busy (in-flight %s) — backing off "
                          "to the next poll", e.version)
                return {"status": "swap-busy",
                        "in_flight": e.version,
                        "candidate": cand["version"]}
            except CanaryRejectedError:
                raise
            except (ReplicaLostError, TimeoutError, _FutTimeout,
                    MXNetError) as e:
                # a replica died (or the transport wedged) mid-swap.
                # The router's swap contract already guarantees the
                # fleet keeps serving — each request is single-version,
                # untouched replicas hold the incumbent — and `_live`
                # was not advanced, so the candidate stays eligible:
                # retry the whole canary on the next poll once the
                # router's health loop has dealt with the lost replica.
                self._swap_failures += 1
                _LOG.error("loop: swap of version %d failed (%s) — "
                           "fleet keeps serving; will retry next poll",
                           cand["version"], e)
                return {"status": "swap-failed",
                        "candidate": cand["version"],
                        "error": str(e)}
        finally:
            sp.end()

    def _canary_and_promote(self, cand):
        version, ckpt = cand["version"], cand["checkpoint"]
        if self._vetted is not None and self._vetted[0] == version:
            # this version already PASSED its canary; the promote roll
            # aborted partway, so some replicas may already serve it — a
            # fresh canary pick could score the candidate as its own
            # "incumbent".  The verdict stands: resume the roll.
            _, inc, can = self._vetted
            _LOG.info("loop: resuming aborted promote of version %d",
                      version)
            return self._promote(cand, inc, can)
        sp = _trace.start_span("loop.canary", cat="loop", version=version)
        try:
            rid, replica = self._pick_canary()
            try:
                incumbent_score = self._score_replica(replica, version,
                                                      phase="incumbent")
            except (MXNetError, ReplicaLostError, TimeoutError,
                    _FutTimeout) as e:
                # nothing was swapped yet: this is an eval problem, not
                # a swap problem — count it as such, retry next poll
                self._eval_failures += 1
                _LOG.error("loop: incumbent eval before canary of "
                           "version %d failed (%s) — will retry next "
                           "poll", version, e)
                return {"status": "eval-failed", "phase": "incumbent",
                        "candidate": version, "error": str(e)}
            self.router.swap_one(rid, checkpoint_dir=ckpt,
                                 version=version)
            try:
                canary_score = self._score_replica(replica, version,
                                                   phase="canary")
            except (MXNetError, ReplicaLostError, TimeoutError,
                    _FutTimeout) as e:
                # fail CLOSED: an unscorable candidate is a rejected one
                self._eval_failures += 1
                _LOG.error("loop: canary eval of version %d failed (%s)",
                           version, e)
                canary_score = float("-inf")
            ok = (canary_score == canary_score          # not NaN
                  and canary_score >= incumbent_score - self.canary_tol)
        finally:
            sp.end()
        if ok:
            # record the verdict BEFORE rolling: if swap_weights aborts
            # partway, the next poll resumes the promote instead of
            # canarying against a partially-rolled fleet
            self._vetted = (version, incumbent_score, canary_score)
            return self._promote(cand, incumbent_score, canary_score)
        return self._reject(cand, rid, incumbent_score, canary_score)

    # --------------------------------------------------- promote / reject
    def _promote(self, cand, incumbent_score, canary_score):
        version, ckpt = cand["version"], cand["checkpoint"]
        sp = _trace.start_span("loop.promote", cat="loop", version=version)
        try:
            self.router.swap_weights(checkpoint_dir=ckpt, version=version)
        finally:
            sp.end()
        self._vetted = None
        self._live = cand
        self._promotions += 1
        lag = self._measure_freshness(cand)
        _LOG.info("loop: promoted version %d (canary %.4f vs incumbent "
                  "%.4f, freshness lag %.1fs)", version, canary_score,
                  incumbent_score, lag if lag is not None else -1.0)
        return {"status": "promoted", "version": version,
                "incumbent_score": incumbent_score,
                "canary_score": canary_score,
                "freshness_lag_s": lag}

    def _reject(self, cand, rid, incumbent_score, canary_score):
        version = cand["version"]
        # roll the canary replica back to the incumbent BEFORE anything
        # else: the poisoned weights must not serve one extra request
        self._restore_canary(rid)
        try:
            self.registry.reject(version, reason="canary",
                                 incumbent_score=incumbent_score,
                                 canary_score=canary_score)
        except MXNetError as e:
            _LOG.error("loop: could not stamp version %d rejected: %s",
                       version, e)
        # stamp the checkpoint itself too, so trainer-side resume and
        # latest_healthy() skip it even without reading the registry.
        # With publish(pin=True) the record's "checkpoint" is the
        # registry-owned blobs/ copy — the trainer resumes from its own
        # ckpt-* directory, so the SOURCE path must carry the stamp too
        from ..checkpoint import manifest as _manifest
        stamped = set()
        for path in (cand.get("checkpoint"),
                     cand.get("source_checkpoint")):
            if not path or path in stamped:
                continue
            stamped.add(path)
            try:
                _manifest.stamp_rejected(path, reason="canary",
                                         incumbent_score=incumbent_score,
                                         canary_score=canary_score)
            except (OSError, MXNetError) as e:
                _LOG.warning("loop: could not stamp checkpoint %s of "
                             "version %d rejected: %s", path, version, e)
        self._rejections += 1
        raise CanaryRejectedError(version, incumbent_score, canary_score,
                                  tol=self.canary_tol)

    def _restore_canary(self, rid, incumbent_ckpt=None):
        if incumbent_ckpt is None:
            incumbent_ckpt = self._live["checkpoint"] if self._live \
                else self.incumbent_checkpoint
        try:
            if incumbent_ckpt is not None:
                self.router.swap_one(rid, checkpoint_dir=incumbent_ckpt)
            else:
                # no known-good checkpoint to restore from: the poisoned
                # replica must not serve — drop it from the fleet
                _LOG.error("loop: no incumbent checkpoint to restore "
                           "canary replica '%s' — declaring it lost", rid)
                self.router.declare_lost(rid)
        except SwapInProgressError as e:
            # an external roll holds the swap lock: the replica is
            # healthy, just serving the rejected weights one poll longer
            # — defer the restore and retry it first thing next poll
            # instead of destroying capacity
            self._swap_busy += 1
            self._pending_restore = (rid, incumbent_ckpt)
            _LOG.warning("loop: restore of canary replica '%s' blocked "
                         "by in-flight swap (%s) — will retry next poll",
                         rid, e.version)
        except (MXNetError, ReplicaLostError) as e:
            _LOG.error("loop: could not restore canary replica '%s' — "
                       "declaring it lost: %s", rid, e)
            try:
                self.router.declare_lost(rid)
            except MXNetError:
                pass

    # --------------------------------------------------------- scoring
    def _pick_canary(self):
        for rid in self.router.replicas():
            try:
                return rid, self.router.replica(rid)
            except MXNetError:
                continue
        raise MXNetError("loop: no live replica to canary on")

    def _score_replica(self, replica, version, phase):
        _faults.fire("canary.eval", version=version, phase=phase)
        fut = replica.submit(dict(self.holdout_inputs),
                             timeout_ms=self.eval_timeout_ms)
        try:
            outputs = fut.result(
                timeout=self.eval_timeout_ms / 1000.0 + 5.0)
        except _FutTimeout as e:
            # translate at the source: pre-3.11 this is not the builtin
            # TimeoutError, and a hung eval must hit the fail-closed
            # handlers, not escape them
            raise MXNetError(
                f"loop: holdout eval of version {version} ({phase}) "
                f"timed out after {self.eval_timeout_ms} ms") from e
        return float(self.score_fn(outputs, self.holdout_labels))

    # ------------------------------------------------------- freshness
    def _measure_freshness(self, cand):
        wm_time = (cand.get("watermark") or {}).get("time")
        if wm_time is None:
            wm_time = cand.get("published_unix")
        if wm_time is None:
            return None
        lag = max(0.0, time.time() - float(wm_time))
        self._freshness_lag_s = lag
        self._gauge.set(lag)
        return lag

    # ------------------------------------------------------ background
    def adopt(self, record):
        """Declare `record` (a registry record) already live — used when
        the fleet booted from the version's checkpoint directly."""
        self._live = record
        if record is not None:
            self._measure_freshness(record)

    def start(self):
        """Poll in a daemon thread until `stop()`."""
        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            self._thread = threading.Thread(target=self._run,
                                            name="mx-loop-controller",
                                            daemon=True)
            self._thread.start()
        return self

    def _run(self):
        while not self._stop.is_set():
            try:
                self.poll_once()
            except CanaryRejectedError as e:
                _LOG.error("loop: %s", e)
            except (MXNetError, ReplicaLostError, TimeoutError,
                    _FutTimeout) as e:
                _LOG.error("loop: poll failed: %s", e)
            self._stop.wait(self.poll_interval_s)

    def stop(self):
        self._stop.set()
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=30.0)

    close = stop

    # ------------------------------------------------------------ stats
    def stats(self):
        out = {
            "polls": self._polls,
            "promotions": self._promotions,
            "canary_rejections": self._rejections,
            "swap_busy": self._swap_busy,
            "swap_failures": self._swap_failures,
            "registry_errors": self._registry_errors,
            "eval_failures": self._eval_failures,
            "live_version": self._live["version"] if self._live else -1,
            "freshness_slo_s": self.freshness_slo_s,
        }
        if self._freshness_lag_s is not None:
            out["freshness_lag_s"] = self._freshness_lag_s
            out["freshness_slo_met"] = int(
                self._freshness_lag_s <= self.freshness_slo_s)
        return out
