"""Pure-function decode plane for the transformer LM.

Training owns the (B, T) full-sequence graph; serving owns two other
programs built from the SAME parameters:

* **prefill** — one bucketed-length forward of a single new sequence
  that writes its K/V into an assigned cache slot and returns the
  first generated token.  One compiled signature per prompt bucket.
* **decode step** — ONE fixed-shape program advancing every slot by
  one token against the cache.  Its input signature never changes
  (slots, max_len and the parameter shapes are baked), so the steady
  state runs zero XLA compiles no matter how sequences arrive, finish,
  or interleave.

The KV cache is a donated carry: both programs consume their cache
arguments (`donate_argnums`) and return the updated cache, so HBM
holds one copy regardless of decode depth — the same donation
discipline as the fused train step, through the same
`compile.cached_jit` tiers (disk-warm processes spin up with zero
compiles).

Everything here is torch-free math on stacked parameters:
`stack_lm_params` turns a trained Module/gluon parameter dict into
per-layer arrays stacked on a leading L axis, so both programs scan
one layer body instead of unrolling N copies — mirroring the
scan-over-layers dedup the training graph gets from `scan_plan`.
"""
from __future__ import annotations

import re

import numpy as np

__all__ = ["stack_lm_params", "init_kv_cache", "DecodePrograms"]

_NEG = -1e30

# suffix -> stacked key; every transformer block parameter the decode
# plane needs, in one table so a missing/renamed parameter fails loudly
_LAYER_SUFFIXES = {
    "ln1_gamma": "ln1_gamma", "ln1_beta": "ln1_beta",
    "qkv_weight": "qkv_weight", "qkv_bias": "qkv_bias",
    "out_proj_weight": "out_weight", "out_proj_bias": "out_bias",
    "ln2_gamma": "ln2_gamma", "ln2_beta": "ln2_beta",
    "fc1_weight": "fc1_weight", "fc1_bias": "fc1_bias",
    "fc2_weight": "fc2_weight", "fc2_bias": "fc2_bias",
}


def _as_np(a):
    return a.asnumpy() if hasattr(a, "asnumpy") else np.asarray(a)


def stack_lm_params(arg_params, cfg):
    """Trained parameter dict -> stacked decode pytree.

    Accepts the `Module.get_params()` arg dict (or any name->array
    mapping with the `llm.model` naming scheme; NDArray or numpy
    values).  Returns ``{"embed", "final_ln_gamma", "final_ln_beta",
    "layers": {suffix: (L, ...)}}`` as jax arrays.
    """
    import jax.numpy as jnp
    from ..base import MXNetError
    names = dict(arg_params)

    def find(suffix):
        hits = [k for k in names if k.endswith(suffix)]
        if len(hits) != 1:
            raise MXNetError(
                "stack_lm_params: expected exactly one parameter ending "
                "with %r, found %r" % (suffix, sorted(hits)))
        return _as_np(names[hits[0]])

    out = {"embed": jnp.asarray(find("embed_weight")),
           "final_ln_gamma": jnp.asarray(find("final_ln_gamma")),
           "final_ln_beta": jnp.asarray(find("final_ln_beta"))}
    layers = {}
    for i in range(cfg.num_layers):
        for suffix, key in _LAYER_SUFFIXES.items():
            layers.setdefault(key, []).append(
                find("block%d_%s" % (i, suffix)))
    out["layers"] = {k: jnp.asarray(np.stack(v)) for k, v in layers.items()}
    return out


def init_kv_cache(cfg, slots):
    """Zeroed (cache_k, cache_v), each (L, slots, max_len, H, D)."""
    import jax.numpy as jnp
    shape = (cfg.num_layers, int(slots), cfg.max_len, cfg.num_heads,
             cfg.head_dim)
    dtype = jnp.dtype(cfg.param_dtype)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


# ---------------------------------------------------------------------------
# layer math (must match ops used by llm/model.py exactly: LayerNorm
# eps 1e-5, exact gelu, 1/sqrt(D)-scaled attention)
# ---------------------------------------------------------------------------

def _ln(x, gamma, beta, eps=1e-5):
    import jax
    import jax.numpy as jnp
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + eps) * gamma + beta


def _layer_full(h, lp, heads, attn_block_size):
    """Full-sequence block forward; returns (h_out, (k, v)) with k/v
    shaped (B, T, H, D) for the prefill cache write."""
    import jax
    import jax.numpy as jnp
    from ..parallel.ring_attention import blockwise_attention
    b, t, c = h.shape
    d = c // heads
    hn = _ln(h, lp["ln1_gamma"], lp["ln1_beta"])
    qkv = hn @ lp["qkv_weight"].T + lp["qkv_bias"]
    q, k, v = (a.reshape(b, t, heads, d)
               for a in jnp.split(qkv, 3, axis=-1))
    attn = blockwise_attention(q, k, v, block_size=attn_block_size,
                               causal=True)
    h = h + attn.reshape(b, t, c) @ lp["out_weight"].T + lp["out_bias"]
    hn = _ln(h, lp["ln2_gamma"], lp["ln2_beta"])
    f = jax.nn.gelu(hn @ lp["fc1_weight"].T + lp["fc1_bias"],
                    approximate=False)
    h = h + f @ lp["fc2_weight"].T + lp["fc2_bias"]
    return h, (k, v)


def _layer_step(h, lp, ck, cv, positions, heads):
    """One-token block forward against the slot cache.

    h (S, C) current activations; ck/cv (S, M, H, D) this layer's
    cache; positions (S,) the index each slot's new K/V lands at.
    Returns (h_out, ck, cv) with the new K/V written in.
    """
    import jax
    import jax.numpy as jnp
    s, c = h.shape
    m = ck.shape[1]
    d = c // heads
    hn = _ln(h, lp["ln1_gamma"], lp["ln1_beta"])
    qkv = hn @ lp["qkv_weight"].T + lp["qkv_bias"]
    q, k, v = (a.reshape(s, heads, d) for a in jnp.split(qkv, 3, axis=-1))

    def put(cache_row, new, pos):
        z = jnp.zeros((), pos.dtype)
        return jax.lax.dynamic_update_slice(cache_row, new[None], (pos, z, z))

    ck = jax.vmap(put)(ck, k, positions)
    cv = jax.vmap(put)(cv, v, positions)
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, dtype=h.dtype))
    scores = jnp.einsum("shd,smhd->shm", q, ck) * scale
    visible = jnp.arange(m)[None, :] <= positions[:, None]     # (S, M)
    scores = jnp.where(visible[:, None, :], scores,
                       jnp.asarray(_NEG, dtype=scores.dtype))
    probs = jax.nn.softmax(scores, axis=-1)
    attn = jnp.einsum("shm,smhd->shd", probs, cv).reshape(s, c)
    h = h + attn @ lp["out_weight"].T + lp["out_bias"]
    hn = _ln(h, lp["ln2_gamma"], lp["ln2_beta"])
    f = jax.nn.gelu(hn @ lp["fc1_weight"].T + lp["fc1_bias"],
                    approximate=False)
    return h + f @ lp["fc2_weight"].T + lp["fc2_bias"], ck, cv


# ---------------------------------------------------------------------------
# programs
# ---------------------------------------------------------------------------

class DecodePrograms:
    """The two cached-jit programs of the decode plane.

    One `CachedProgram` per logical graph: ``prefill`` accumulates one
    compiled signature per prompt bucket; ``step`` holds exactly one.
    Both donate their cache arguments.  `program_count()` is the
    zero-recompile certification hook (same contract as
    `FusedInference.program_count`).
    """

    def __init__(self, cfg, params, label="lm"):
        from ..compile import cached_jit, graph_hash_of_text
        self.cfg = cfg
        self.params = params
        sig = [(k, tuple(v.shape), str(v.dtype))
               for k, v in sorted(params["layers"].items())]
        base = graph_hash_of_text("llm-decode", cfg.to_dict(), sig,
                                  tuple(params["embed"].shape))
        heads, bs = cfg.num_heads, cfg.attn_block_size

        def prefill(p, ck, cv, tokens, slot, length):
            import jax
            import jax.numpy as jnp
            emb = p["embed"][tokens]                     # (1, Tb, C)

            def body(h, lp):
                h, kv = _layer_full(h, lp, heads, bs)
                return h, kv

            h, (ks, vs) = jax.lax.scan(body, emb, p["layers"])
            # ks (L, 1, Tb, H, D) -> cache rows [l, slot, :Tb]
            z = jnp.zeros((), jnp.int32)
            start = (z, jnp.asarray(slot).astype(jnp.int32), z, z, z)
            ck = jax.lax.dynamic_update_slice(ck, ks, start)
            cv = jax.lax.dynamic_update_slice(cv, vs, start)
            hn = _ln(h, p["final_ln_gamma"], p["final_ln_beta"])
            logits = hn[0, length - 1] @ p["embed"].T    # (V,)
            return ck, cv, jnp.argmax(logits).astype(jnp.int32), logits

        def step(p, ck, cv, tokens, positions):
            import jax
            import jax.numpy as jnp
            h = p["embed"][tokens]                       # (S, C)

            def body(carry, xs):
                lp, ck_l, cv_l = xs
                h, ck_l, cv_l = _layer_step(carry, lp, ck_l, cv_l,
                                            positions, heads)
                return h, (ck_l, cv_l)

            h, (ck, cv) = jax.lax.scan(body, h, (p["layers"], ck, cv))
            hn = _ln(h, p["final_ln_gamma"], p["final_ln_beta"])
            logits = hn @ p["embed"].T                   # (S, V)
            return ck, cv, jnp.argmax(logits, axis=-1).astype(jnp.int32), \
                logits

        self.prefill = cached_jit(prefill, donate_argnums=(1, 2),
                                  graph_key=base + "-prefill",
                                  label="%s.prefill" % label)
        self.step = cached_jit(step, donate_argnums=(1, 2),
                               graph_key=base + "-step",
                               label="%s.step" % label)

    def program_count(self):
        return self.prefill._cache_size() + self.step._cache_size()

    def compile_count(self):
        return self.prefill.compile_count + self.step.compile_count

    def warmup(self, slots, buckets):
        """Compile every signature the engine will ever dispatch: one
        prefill per bucket plus the decode step, against a scratch
        cache (donation consumes it; the engine's live cache is built
        after).  Returns the number of cold compiles this cost."""
        import jax.numpy as jnp
        from .. import fused as _fused
        before = self.compile_count()
        ck, cv = init_kv_cache(self.cfg, slots)
        # donation safety: never hand a possibly-host-staged buffer to
        # a donating AOT program (see fused.reown_for_donation)
        ck, cv = _fused.reown_for_donation((ck, cv))
        for b in sorted(set(int(x) for x in buckets)):
            tokens = jnp.zeros((1, b), jnp.int32)
            ck, cv, _, _ = self.prefill(self.params, ck, cv, tokens,
                                        jnp.int32(0), jnp.int32(1))
        s = ck.shape[1]
        ck, cv, _, _ = self.step(self.params, ck, cv,
                                 jnp.zeros((s,), jnp.int32),
                                 jnp.zeros((s,), jnp.int32))
        del ck, cv
        return self.compile_count() - before
