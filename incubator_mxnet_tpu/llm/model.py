"""Gluon Transformer LM and its `Module.fit` training symbol.

Architecture (pre-norm GPT):

    tokens (B, T) --Embedding--> (B, T, C)
      N x [ LN -> qkv FC -> BlockwiseAttention -> out_proj FC -> +res
            LN -> fc1 FC -> gelu -> fc2 FC -> +res ]
      final LN -> tied head (FullyConnected against the embedding
      weight, no bias) -> logits (B, T, V)

Parameter names are chosen to hit the megatron sharding regexes
(`parallel/tensor_parallel.ShardingRules.megatron`): ``*qkv_weight``
and ``*fc1_weight`` column-parallel, ``*out_proj_weight`` and
``*fc2_weight`` row-parallel, ``*embed_weight`` vocab-sharded — so
`Module.init_optimizer(mesh="dp=A,tp=B")` shards the LM with no
per-model rule table.

The N blocks are graph-identical (same op sequence, same attrs, only
parameter names differ), which is exactly the shape
`analysis/graph_passes.scan_plan` deduplicates: the stack compiles as
one scanned block body instead of N copies (tests/test_llm.py locks
this).
"""
from __future__ import annotations

from dataclasses import dataclass, field, asdict

from ..gluon import nn
from ..gluon.block import HybridBlock


@dataclass
class LMConfig:
    """Static LM shape shared by training, serving and the bench."""
    vocab_size: int = 256
    num_layers: int = 2
    num_heads: int = 2
    hidden: int = 32
    ffn_mult: int = 4
    max_len: int = 64            # KV-cache capacity per decode slot
    attn_block_size: int = None  # None: blockwise kernel picks its tile
    eos_id: int = 0
    param_dtype: str = "float32"

    def to_dict(self):
        return asdict(self)

    @classmethod
    def from_dict(cls, d):
        names = {f.name for f in cls.__dataclass_fields__.values()} \
            if isinstance(cls.__dataclass_fields__, dict) else set()
        return cls(**{k: v for k, v in d.items()
                      if k in cls.__dataclass_fields__})

    @property
    def head_dim(self):
        return self.hidden // self.num_heads


# ops emitted per transformer block by TransformerBlock.hybrid_forward:
# ln1, qkv FC, 3x slice_axis, attention, out_proj FC, residual add,
# ln2, fc1 FC, gelu, fc2 FC, residual add
_BLOCK_OPS = 13


def lm_block_op_count():
    """Symbol nodes per transformer block — the repetition period
    `scan_plan` must discover when grouping the stack."""
    return _BLOCK_OPS


class TransformerBlock(HybridBlock):
    """One pre-norm transformer block (attention + MLP)."""

    def __init__(self, hidden, num_heads, ffn_mult=4, attn_block_size=None,
                 dtype="float32", **kwargs):
        super().__init__(**kwargs)
        self._hidden = int(hidden)
        self._heads = int(num_heads)
        self._attn_block_size = attn_block_size
        with self.name_scope():
            self.ln1 = nn.LayerNorm(in_channels=hidden, prefix="ln1_")
            self.qkv = nn.Dense(3 * hidden, flatten=False, in_units=hidden,
                                dtype=dtype, prefix="qkv_")
            self.out_proj = nn.Dense(hidden, flatten=False, in_units=hidden,
                                     dtype=dtype, prefix="out_proj_")
            self.ln2 = nn.LayerNorm(in_channels=hidden, prefix="ln2_")
            self.fc1 = nn.Dense(ffn_mult * hidden, flatten=False,
                                in_units=hidden, dtype=dtype, prefix="fc1_")
            self.fc2 = nn.Dense(hidden, flatten=False,
                                in_units=ffn_mult * hidden, dtype=dtype,
                                prefix="fc2_")

    def hybrid_forward(self, F, x):
        c = self._hidden
        h = self.ln1(x)
        qkv = self.qkv(h)
        q = F.slice_axis(qkv, axis=-1, begin=0, end=c)
        k = F.slice_axis(qkv, axis=-1, begin=c, end=2 * c)
        v = F.slice_axis(qkv, axis=-1, begin=2 * c, end=3 * c)
        attn = F.BlockwiseAttention(q, k, v, num_heads=self._heads,
                                    causal=True,
                                    block_size=self._attn_block_size)
        x = x + self.out_proj(attn)
        h = self.ln2(x)
        h = self.fc1(h)
        h = F.LeakyReLU(h, act_type="gelu")
        return x + self.fc2(h)


class TransformerLM(HybridBlock):
    """Embedding -> N identical blocks -> final LN -> tied head."""

    def __init__(self, cfg, **kwargs):
        super().__init__(**kwargs)
        self.cfg = cfg
        with self.name_scope():
            # one parameter serves both faces: Embedding lookup on the
            # way in, FullyConnected weight (tied head) on the way out
            self.embed_weight = self.params.get(
                "embed_weight", shape=(cfg.vocab_size, cfg.hidden),
                dtype=cfg.param_dtype, allow_deferred_init=True)
            self.blocks = nn.HybridSequential(prefix="")
            for i in range(cfg.num_layers):
                self.blocks.add(TransformerBlock(
                    cfg.hidden, cfg.num_heads, ffn_mult=cfg.ffn_mult,
                    attn_block_size=cfg.attn_block_size,
                    dtype=cfg.param_dtype, prefix="block%d_" % i))
            self.final_ln = nn.LayerNorm(in_channels=cfg.hidden,
                                         prefix="final_ln_")

    def hybrid_forward(self, F, tokens, embed_weight):
        cfg = self.cfg
        h = F.Embedding(tokens, embed_weight, input_dim=cfg.vocab_size,
                        output_dim=cfg.hidden)
        h = self.blocks(h)
        h = self.final_ln(h)
        return F.FullyConnected(h, embed_weight,
                                num_hidden=cfg.vocab_size,
                                no_bias=True, flatten=False)


def lm_symbol(cfg, prefix="lm_"):
    """`Module.fit`-ready training graph: next-token cross-entropy.

    data (B, T) int32 tokens; softmax_label (B, T) int32 targets
    (the caller shifts).  Logits flatten to (B*T, V) through
    `SoftmaxOutput` exactly like the bench LSTM head.
    """
    from .. import symbol as sym
    model = TransformerLM(cfg, prefix=prefix)
    data = sym.Variable("data")
    logits = model(data)                     # (B, T, V)
    pred = sym.Reshape(logits, shape=(-1, cfg.vocab_size))
    label = sym.Variable("softmax_label")
    label = sym.Reshape(label, shape=(-1,))
    return sym.SoftmaxOutput(pred, label, name="softmax")
