"""Transformer language model: the flagship attention workload.

`model.py` defines the gluon `TransformerLM` (embedding, N identical
pre-norm blocks over the registered `BlockwiseAttention` op, tied
output head) and `lm_symbol`, its `Module.fit`-ready training graph.
`decode_core.py` holds the pure-function decode plane: stacked
per-layer parameters scanned by one fixed-shape decode-step program
and per-bucket prefill programs, with the KV cache as a donated carry
— what `serving/decode.py`'s continuous-batching `DecodeEngine` runs.
"""
from .model import (LMConfig, TransformerBlock, TransformerLM, lm_symbol,
                    lm_block_op_count)
from .decode_core import (DecodePrograms, stack_lm_params, init_kv_cache)

__all__ = ["LMConfig", "TransformerBlock", "TransformerLM", "lm_symbol",
           "lm_block_op_count", "DecodePrograms", "stack_lm_params",
           "init_kv_cache"]
