"""Training callbacks (reference `python/mxnet/callback.py`)."""
from __future__ import annotations

import logging
import math
import time


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """Checkpoint callback for Module (reference `callback.py module_checkpoint`)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states)
    return _callback


def do_checkpoint(prefix, period=1):
    """Per-epoch checkpoint callback (reference `callback.py do_checkpoint`)."""
    from .model import save_checkpoint
    period = int(max(1, period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)
    return _callback


def elastic_checkpoint(manager, mod, train_data=None, period=1):
    """Batch-end callback taking async full-state snapshots through a
    `checkpoint.CheckpointManager` — the wiring for training loops that
    drive `fit_step` themselves instead of `Module.fit(checkpoint_dir=)`.

    Unlike `module_checkpoint` (epoch-grained, synchronous, params+states
    as loose files) this captures optimizer slots, iterator position and
    RNG streams into one atomically-committed checkpoint directory while
    the train step keeps running.

    For custom loops stepping `fit_step` per batch.  Under `Module.fit`
    prefer ``fit(checkpoint_dir=...)``: its fused block mode fires
    batch-end callbacks in post-block bursts where ``param.nbatch`` lags
    the already-applied updates, so a snapshot from inside the burst
    records a position resume would replay (fit's built-in path
    snapshots at block boundaries, where position and params agree)."""
    period = int(max(1, period))
    counter = {"step": 0}

    def _callback(param):
        counter["step"] += 1
        if counter["step"] % period:
            return
        from .checkpoint import state as _state
        arrays, blobs = _state.capture_module(mod, train_data)
        manager.snapshot(arrays=arrays, blobs=blobs, step=counter["step"],
                         epoch=param.epoch, nbatch=param.nbatch + 1)
    return _callback


def log_train_metric(period, auto_reset=False):
    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()
    return _callback


class Speedometer:
    """samples/sec logger (reference `callback.py:Speedometer`)."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.init = False
        self.tic = 0
        self.last_count = 0
        self.auto_reset = auto_reset

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count

        if self.init:
            if count % self.frequent == 0:
                speed = self.frequent * self.batch_size / (time.time() - self.tic)
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    if self.auto_reset:
                        param.eval_metric.reset()
                    msg = "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec"
                    msg += "\t%s=%f" * len(name_value)
                    logging.info(msg, param.epoch, count, speed,
                                 *sum(name_value, ()))
                else:
                    logging.info("Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                                 param.epoch, count, speed)
                self.tic = time.time()
        else:
            self.init = True
            self.tic = time.time()


class ProgressBar:
    """Reference `callback.py:ProgressBar`."""

    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = math.ceil(100.0 * count / float(self.total))
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        logging.info("[%s] %s%s\r", prog_bar, percents, "%")
