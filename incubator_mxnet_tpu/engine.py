"""Execution-engine semantics over JAX's async dispatch.

The reference's dependency engine (`src/engine/threaded_engine.cc`,
`include/mxnet/engine.h:116-315`) provides: (1) async op execution with
sequential consistency per variable, (2) `WaitForVar` / `WaitForAll` sync
points, (3) a serializing `NaiveEngine` debug mode, (4) bulk-execution fusion.

On TPU, XLA/PJRT already gives (1): `jax` dispatch is asynchronous and PJRT
buffer semantics preserve per-buffer ordering (read-after-write etc.), so we
do not rebuild a threaded scheduler for device compute.  What remains host-side
is bookkeeping for the sync points and the debug mode:

* every eagerly-dispatched output array is registered in a weak set so
  `waitall()` (reference `MXNDArrayWaitAll`) can block on everything in flight;
* ``MXNET_ENGINE_TYPE=NaiveEngine`` forces a block after every op, matching
  the reference's serializing debug engine (`src/engine/naive_engine.cc:50`);
* `bulk(size)` implements the reference's bulk-execution fusion
  (`include/mxnet/engine.h:308-313`) for the *host→device* direction: inside a
  bulk scope, pure creation ops (zeros/ones/initializers) stage numpy buffers
  host-side and the scope exit performs ONE batched `jax.device_put` per
  device instead of one dispatch per array.  On the experimental tunnel
  platform each dispatch costs ~100ms, so unbatched init of a ResNet-50
  (~270 arrays) costs minutes; bulk init costs one transfer.
"""
from __future__ import annotations

import os
import weakref

from .analysis import locks as _alocks

__all__ = ["waitall", "wait_to_read", "bulk", "set_bulk_size", "engine_type",
           "bulk_active", "stage", "flush_staged"]

_lock = _alocks.make_lock("engine")
_in_flight = weakref.WeakSet()


def engine_type():
    return os.environ.get("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice")


def _naive():
    return engine_type() == "NaiveEngine"


def track(jarr, op=None):
    """Register a dispatched jax.Array; block immediately under NaiveEngine.

    `op` names the originating operator: NaiveEngine exists to surface
    deferred errors AT the op that caused them, so its failure is chained
    into a contextful MXNetError naming that op instead of re-raising the
    bare XLA error with no attribution."""
    import jax.core as _jc
    if isinstance(jarr, _jc.Tracer):
        # abstract value inside a jax trace (fused train step / CachedOp):
        # nothing is in flight, and a leaked tracer in the wait-set would
        # outlive its trace
        return jarr
    if _naive():
        try:
            jarr.block_until_ready()
        except Exception as e:
            from .base import MXNetError
            raise MXNetError(
                f"NaiveEngine: operator '{op or '<unknown>'}' failed "
                f"during synchronous execution: {e}") from e
        return jarr
    try:
        with _lock:
            _in_flight.add(jarr)
    except TypeError:
        pass
    return jarr


def wait_to_read(jarr):
    """Block until an array's value is ready (reference `NDArray::WaitToRead`)."""
    block = getattr(jarr, "block_until_ready", None)
    if block is not None:  # host-staged numpy buffers are already "ready"
        block()


def waitall():
    """Block until all outstanding async work completes (reference
    `Engine::WaitForAll`, `mx.nd.waitall`)."""
    from .analysis import hostsync as _hostsync
    if _hostsync._active:
        _hostsync.note("waitall")
    with _lock:
        arrs = list(_in_flight)
        _in_flight.clear()
    for a in arrs:
        try:
            a.block_until_ready()
        except Exception:
            raise


_bulk_size = 0
_staging_depth = 0  # nesting depth of active bulk() scopes
_staged = []  # NDArrays whose _data is a host numpy buffer awaiting transfer
_staged_ids = set()


def set_bulk_size(size):
    """Reference `Engine::set_bulk_size` (`include/mxnet/engine.h:308-313`).

    Device-side op fusion is subsumed by whole-graph XLA compilation; the
    knob is kept for API parity.  Host-staging activates only inside the
    `bulk()` context manager (which guarantees a flush on exit).  Returns
    the previous value.
    """
    global _bulk_size
    prev, _bulk_size = _bulk_size, size
    return prev


def bulk_active():
    """True while inside a bulk scope (creation ops should host-stage)."""
    return _staging_depth > 0 and _bulk_size != 0


def stage(nd_obj):
    """Register a host-staged NDArray for the next `flush_staged()`."""
    if id(nd_obj) not in _staged_ids:
        _staged_ids.add(id(nd_obj))
        _staged.append(nd_obj)


def unstage(nd_obj):
    """Drop a staged NDArray (e.g. a scratch buffer that was copied away)."""
    if id(nd_obj) in _staged_ids:
        _staged_ids.discard(id(nd_obj))
        for i, a in enumerate(_staged):  # identity, not NDArray.__eq__
            if a is nd_obj:
                del _staged[i]
                break


def flush_staged():
    """Transfer all staged host buffers to their devices, one batched
    `jax.device_put` per target device."""
    import numpy as np
    if not _staged:
        return
    arrs = [a for a in _staged if isinstance(a._data, np.ndarray)]
    del _staged[:]
    _staged_ids.clear()
    if not arrs:
        return
    import jax
    by_dev = {}
    for a in arrs:
        by_dev.setdefault(a.context, []).append(a)
    for ctx, group in by_dev.items():
        bufs = jax.device_put([a._data for a in group], ctx.jax_device)
        for a, b in zip(group, bufs):
            a._data = b


class bulk:
    """Context manager `mx.engine.bulk(size)` (reference `python/mxnet/engine.py`).

    On exit of the outermost scope, staged host buffers are flushed to
    their devices in batched transfers.
    """

    def __init__(self, size):
        self.size = size
        self._prev = None

    def __enter__(self):
        global _staging_depth
        self._prev = set_bulk_size(self.size)
        _staging_depth += 1

    def __exit__(self, *args):
        global _staging_depth
        set_bulk_size(self._prev)
        _staging_depth -= 1
        if _staging_depth == 0:
            flush_staged()
