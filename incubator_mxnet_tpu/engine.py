"""Execution-engine semantics over JAX's async dispatch.

The reference's dependency engine (`src/engine/threaded_engine.cc`,
`include/mxnet/engine.h:116-315`) provides: (1) async op execution with
sequential consistency per variable, (2) `WaitForVar` / `WaitForAll` sync
points, (3) a serializing `NaiveEngine` debug mode, (4) bulk-execution fusion.

On TPU, XLA/PJRT already gives (1): `jax` dispatch is asynchronous and PJRT
buffer semantics preserve per-buffer ordering (read-after-write etc.), so we
do not rebuild a threaded scheduler for device compute.  What remains host-side
is bookkeeping for the sync points and the debug mode:

* every eagerly-dispatched output array is registered in a weak set so
  `waitall()` (reference `MXNDArrayWaitAll`) can block on everything in flight;
* ``MXNET_ENGINE_TYPE=NaiveEngine`` forces a block after every op, matching
  the reference's serializing debug engine (`src/engine/naive_engine.cc:50`);
* `bulk(size)` is kept as an API no-op: whole-graph XLA compilation is the
  TPU-native generalization of bulk mode (`SURVEY.md` §7).
"""
from __future__ import annotations

import os
import weakref
import threading

__all__ = ["waitall", "wait_to_read", "bulk", "set_bulk_size", "engine_type"]

_lock = threading.Lock()
_in_flight = weakref.WeakSet()


def engine_type():
    return os.environ.get("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice")


def _naive():
    return engine_type() == "NaiveEngine"


def track(jarr):
    """Register a dispatched jax.Array; block immediately under NaiveEngine."""
    if _naive():
        try:
            jarr.block_until_ready()
        except Exception:  # deferred errors surface at wait points, like the reference
            raise
        return jarr
    try:
        with _lock:
            _in_flight.add(jarr)
    except TypeError:
        pass
    return jarr


def wait_to_read(jarr):
    """Block until an array's value is ready (reference `NDArray::WaitToRead`)."""
    jarr.block_until_ready()


def waitall():
    """Block until all outstanding async work completes (reference
    `Engine::WaitForAll`, `mx.nd.waitall`)."""
    with _lock:
        arrs = list(_in_flight)
        _in_flight.clear()
    for a in arrs:
        try:
            a.block_until_ready()
        except Exception:
            raise


_bulk_size = 0


def set_bulk_size(size):
    """Reference `Engine::set_bulk_size` (`include/mxnet/engine.h:308-313`).

    Bulk fusion is subsumed by whole-graph XLA compilation; the knob is kept
    for API parity and returns the previous value.
    """
    global _bulk_size
    prev, _bulk_size = _bulk_size, size
    return prev


class bulk:
    """Context manager `mx.engine.bulk(size)` (reference `python/mxnet/engine.py`)."""

    def __init__(self, size):
        self.size = size
        self._prev = None

    def __enter__(self):
        self._prev = set_bulk_size(self.size)

    def __exit__(self, *args):
        set_bulk_size(self._prev)
