"""Multi-process distributed training.

TPU-native re-expression of the reference's ps-lite stack
(`src/kvstore/kvstore_dist.h:44-412` worker, `kvstore_dist_server.h:155-559`
server, `ps-lite/` transport, `tools/launch.py:71` launcher):

* `transport`  — length-prefixed message framing over TCP sockets (the
  ps-lite Van/Customer roles collapsed to one framed request/response
  channel; localhost and DCN both work).
* `server`     — the parameter-server process: aggregates sync pushes from
  all workers, applies the optimizer server-side when one is attached
  (`kvstore_dist_server.h` DataHandleDefault), and answers versioned pulls.
* `kvstore_dist` — the worker-side KVStore: reduces local device shards
  with the single-collective engine (kvstore.KVStoreTPU), then pushes one
  merged array per key over the wire.
* `collective` — `jax.distributed` bootstrap for real multi-host TPU pods,
  where push/pull lower to XLA collectives over ICI/DCN instead of the
  socket server (the NCCL/MPI replacement).

Env contract (names kept from the reference's dmlc tracker so existing
launch tooling maps 1:1): DMLC_ROLE, DMLC_PS_ROOT_URI, DMLC_PS_ROOT_PORT,
DMLC_NUM_WORKER, DMLC_NUM_SERVER, DMLC_RANK.
"""
from . import collective, transport
from .kvstore_dist import KVStoreDist

__all__ = ["collective", "transport", "KVStoreDist", "ParameterServer"]


def __getattr__(name):
    # lazy: `python -m incubator_mxnet_tpu.dist.server` would otherwise
    # import server via the package first (runpy double-import warning)
    if name == "ParameterServer":
        from .server import ParameterServer
        return ParameterServer
    raise AttributeError(name)
