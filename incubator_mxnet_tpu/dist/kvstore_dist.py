"""Worker-side distributed KVStore.

The `src/kvstore/kvstore_dist.h:44-412` role: locally reduce the per-device
gradient shards (one XLA all-reduce over the chip mesh — KVStoreTPU's
engine), then exchange ONE merged array per key with the parameter server
over the socket transport.  `dist_sync` aggregates a round across all
workers before anyone observes it; `dist_async` applies pushes immediately.

The reference encodes worker identity via the dmlc tracker env
(DMLC_RANK/DMLC_NUM_WORKER etc.); the same names are honored here so
`tools/launch.py` and existing cluster scripts port directly.
"""
from __future__ import annotations

import os
import pickle

from ..base import MXNetError
from ..kvstore import (KVStoreTPU, _normalize, _normalize_push, _key,
                       _updater_key)
from ..resilience import CircuitBreaker, ServerLostError, faults as _faults
from .transport import Channel


class KVStoreDist(KVStoreTPU):
    def __init__(self, kind="dist_sync"):
        super().__init__(kind)
        self._sync = "async" not in kind
        host = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        port = int(os.environ.get("DMLC_PS_ROOT_PORT", 9091))
        self._chan = Channel(host, port)
        env_rank = os.environ.get("DMLC_RANK")
        from .. import config as _config
        # membership epoch fence: a worker restarted by shrink-and-resume
        # carries the post-shrink epoch (MXNET_SUPERVISOR_EPOCH); a stale
        # host registering with an old epoch is refused by the server
        self._epoch = int(_config.get("MXNET_SUPERVISOR_EPOCH"))
        reply = _check(self._chan.request(
            {"cmd": "register", "role": "worker", "epoch": self._epoch,
             "rank": int(env_rank) if env_rank is not None else None}))
        self._rank = reply["rank"]
        self._num_workers = reply["num_workers"]
        # key-range sharding over N servers (reference kvstore_dist.h:44 +
        # docs/faq/distributed_training.md:50-53): whole small keys land
        # on one server by stable hash; arrays over
        # MXNET_KVSTORE_BIGARRAY_BOUND flat-split into one contiguous
        # range per server, each stored under the TRUE key (every server
        # only ever holds its own slice, exactly ps-lite's value ranges)
        self._num_servers = int(reply.get("num_servers", 1))
        self._chans = [self._chan]
        if self._num_servers > 1:
            srv = _check(self._chan.request({"cmd": "server_list"}))
            self._chans += [Channel(h, p) for h, p in srv["servers"]]
        from .. import config as _config
        # per-server health: a consecutive-failure circuit breaker per
        # channel; a tripped breaker is the permanent-death diagnosis that
        # becomes a structured ServerLostError (failover semantics)
        self._breakers = [
            CircuitBreaker(
                failure_threshold=int(_config.get(
                    "MXNET_PS_BREAKER_THRESHOLD")),
                reset_timeout=float(_config.get("MXNET_PS_BREAKER_RESET_S")))
            for _ in self._chans]
        # a reconnected root channel re-handshakes (re-registers under the
        # SAME rank) before the retried request is resent
        rank = self._rank

        def _rehandshake(chan, _rank=rank, _epoch=self._epoch):
            chan.bare_request({"cmd": "register", "role": "worker",
                               "rank": _rank, "epoch": _epoch})
        self._chan.on_reconnect = _rehandshake
        self._bigarray_bound = int(_config.get(
            "MXNET_KVSTORE_BIGARRAY_BOUND"))
        self._push_count = {}    # (srv, key) -> completed sync pushes
        self._update_on_kvstore = False
        # route profiler(profile_process='server') commands through us
        from .. import profiler as _profiler
        _profiler.set_kvstore_handle(self)
        # telemetry plane: the dist retry/failover counters under their
        # own namespace (the base class's bucketed counters stay under
        # 'kvstore' via super().__init__'s registration)
        from ..obs import metrics as _obs_metrics
        _obs_metrics.register_producer("kvstore.dist", self.stats)
        # collective data plane: gradients all-reduce over the global device
        # mesh (ICI/DCN via XLA collectives — the reference's NCCL/ps-lite
        # data role done the TPU way, SURVEY §2.4); the socket server is
        # then control plane only (registration, init, barriers).  sync
        # mode only: async semantics need a mailbox, which is the server.
        self._collective = None
        if self._sync and self._num_workers > 1 and \
                os.environ.get("MXNET_KVSTORE_COLLECTIVE", "1") != "0":
            try:
                self._collective = _CollectivePlane(self._rank,
                                                    self._num_workers)
            except Exception as e:
                import logging
                logging.getLogger(__name__).warning(
                    "collective data plane unavailable (%s); gradients go "
                    "through the parameter server", str(e)[:200])
                self._collective = None

    def _request(self, srv, msg):
        """One control-channel round trip with failover semantics.

        The channel itself retries transient failures (backoff, reconnect,
        idempotent resend — transport.Channel).  This layer tracks
        per-server HEALTH: each exhausted channel-level attempt counts
        against the server's circuit breaker; when the breaker trips the
        server is diagnosed permanently dead and a structured
        `ServerLostError` names the server, its address, and the keys
        whose ranges it owned.  A server that answers but has LOST its
        store (restarted empty) gets the same diagnosis — its state is
        unrecoverable without a checkpoint resume either way."""
        chan = self._chans[srv]
        breaker = self._breakers[srv]
        addr = f"{chan.host}:{chan.port}"
        if not breaker.allow():
            raise ServerLostError(
                srv, addr, keys=self._keys_on(srv),
                reason=f"circuit breaker is {breaker.state} after "
                       f"{breaker.failure_threshold} consecutive failures")
        last = None
        framed = False
        while True:
            try:
                # retries resend the SAME frame (same seq) so a server
                # that already applied it replays its cached reply
                reply = chan.resend_last() if framed else chan.request(msg)
                break
            except TimeoutError as e:
                # slow or wedged, not provably dead: the channel stayed
                # consistent (stale reply discarded by seq).  Resend the
                # SAME frame (the server's dedup/inflight shell absorbs
                # it) until the breaker declares the server unresponsive
                # — a partition with no RST must still reach failover.
                last = e
                framed = True
                if breaker.record_failure():
                    raise ServerLostError(
                        srv, addr, keys=self._keys_on(srv),
                        reason=f"unresponsive during {msg.get('cmd')!r}: "
                               f"{breaker.failure_threshold} consecutive "
                               f"timeouts ({e})") from e
                _faults.note("retry", site="kvstore", server=srv,
                             cmd=msg.get("cmd"), error="timeout")
            except (ConnectionError, EOFError, OSError) as e:
                last = e
                framed = True
                if breaker.record_failure():
                    raise ServerLostError(
                        srv, addr, keys=self._keys_on(srv),
                        reason=f"unreachable during {msg.get('cmd')!r} "
                               f"after {breaker.failure_threshold} "
                               f"consecutive failures "
                               f"({type(last).__name__}: {last})") from last
                _faults.note("reconnect", site="kvstore", server=srv,
                             cmd=msg.get("cmd"))
        if "error" in reply:
            err = reply["error"]
            if "epoch fenced" in err:
                # a shrink committed while this request waited (our own
                # watchdog had not fired yet): surface the recoverable
                # signal, not a generic error — fit's restart loop then
                # drives this worker through the shrink/fence path
                from ..resilience.supervisor import CollectiveTimeoutError
                breaker.record_success()   # the server is alive and sane
                raise CollectiveTimeoutError(
                    f"kvstore.{msg.get('cmd')}", axis="workers",
                    detail=err)
            k = msg.get("key")
            if "has not been initialized" in err and k is not None \
                    and k in self._store:
                # the server answered but forgot a key this worker DID
                # initialize: it restarted empty — its range is gone
                breaker.record_failure()
                raise ServerLostError(
                    srv, addr, keys=self._keys_on(srv),
                    reason=f"server restarted without state ({err})")
            # an application-level error over a WORKING transport still
            # proves the server alive — close any half-open probe
            breaker.record_success()
            raise MXNetError(err)
        breaker.record_success()
        return reply

    def _supervised(self, name, fn):
        """Route a blocking cross-host exchange through the active
        `JobSupervisor`'s hung-collective watchdog (plain call when no
        supervisor is active).  A sync push/pull that a dead host's
        missing contribution can stall forever becomes a structured
        `CollectiveTimeoutError` naming the absent hosts instead."""
        from ..resilience.supervisor import supervised
        return supervised(name, fn, axis="workers")

    def server_addresses(self):
        """Every parameter server's (host, port), root first — the
        shard-server set a `ShardedEmbedding` table partitions over."""
        return [(c.host, c.port) for c in self._chans]

    def embedding(self, name, num_rows, dim, **kwargs):
        """A `ShardedEmbedding` row-sharded over THIS store's servers:
        each server hosts one row shard next to the dense key ranges it
        already owns, so `set_optimizer` / checkpoint state capture
        cover both planes in one place."""
        from ..embedding import ShardedEmbedding
        return ShardedEmbedding(name, num_rows, dim,
                                self.server_addresses(), **kwargs)

    def stats(self):
        """PR 5 retry/failover counters, one dict — exported through
        `JobSupervisor.stats()` into the chaos / run_tpu_parity
        artifacts: per-channel idempotent resends, stale replies
        discarded by sequence number, and every per-server breaker's
        state."""
        return {
            "resends": sum(c.resends for c in self._chans),
            "discarded_stale": sum(c.discarded_stale for c in self._chans),
            "breakers": [
                {"server": i, "addr": f"{c.host}:{c.port}",
                 "state": b.state,
                 "consecutive_failures": b.consecutive_failures}
                for i, (c, b) in enumerate(zip(self._chans,
                                               self._breakers))],
        }

    def _keys_on(self, srv):
        """Keys whose shard routing places a range on server `srv`
        (ServerLostError evidence: what data the lost server owned)."""
        import numpy as _np
        out = []
        for sk, v in self._store.items():
            size = int(_np.prod(v.shape)) if v.shape else 1
            if any(s == srv for s, _ in self._shards(sk, size)):
                out.append(sk)
        return out

    # -- checkpoint plane ------------------------------------------------------
    def get_optimizer_states(self, dump_optimizer=False):
        """Optimizer slots as one bytes blob for the checkpoint plane.

        Server-side optimizer (socket data plane): each server owns the
        slots for ITS key ranges — pull every server's states back through
        the control channel and wrap them per-server, the
        rank-0-writes-params layout's single blob.  Collective mode: the
        optimizer ran worker-side (replicated), so the local updater is
        authoritative."""
        if self._updater is not None:
            return self._updater.get_states(dump_optimizer=dump_optimizer)
        blobs = {}
        for srv in range(len(self._chans)):
            reply = self._request(srv, {"cmd": "get_optimizer_states",
                                        "dump_optimizer": dump_optimizer})
            blobs[srv] = reply.get("states")
        if all(b is None for b in blobs.values()):
            raise MXNetError(
                "get_optimizer_states: no optimizer is installed on any "
                "parameter server (call set_optimizer first)")
        return pickle.dumps({"dist_server_states": blobs}, protocol=4)

    def set_optimizer_states(self, blob):
        """Restore a `get_optimizer_states` blob.  Per-server blobs go
        back to the server that owns each key range (rank 0 pushes, then
        everyone barriers so no worker trains against half-restored
        slots); a worker-side blob loads into the local updater."""
        payload = pickle.loads(blob) if isinstance(blob, bytes) else blob
        if isinstance(payload, dict) and "dist_server_states" in payload:
            if self._rank == 0:
                for srv, states in payload["dist_server_states"].items():
                    if states is None:
                        continue
                    self._request(int(srv), {"cmd": "set_optimizer_states",
                                             "states": states})
            self._barrier()
            return
        if self._updater is None:
            raise MXNetError(
                "set_optimizer_states: blob holds worker-side updater "
                "state but this store has no local updater (collective "
                "mode not engaged?)")
        self._updater.set_states(blob)

    def save_optimizer_states(self, fname, dump_optimizer=False):
        with open(fname, "wb") as f:
            f.write(self.get_optimizer_states(dump_optimizer=dump_optimizer))

    def load_optimizer_states(self, fname):
        with open(fname, "rb") as f:
            self.set_optimizer_states(f.read())

    def server_profiler_command(self, action, **kw):
        """Drive every parameter server's profiler (reference
        `mx.profiler.set_config/set_state/dump(profile_process='server')`
        forwarded through MXKVStoreSendCommmandToServers).  Every server
        is attempted; failures are aggregated so a bad first server
        cannot leave the rest silently unconfigured."""
        errors = []
        for i, chan in enumerate(self._chans):
            try:
                _check(chan.request(dict({"cmd": "profiler",
                                          "action": action}, **kw)))
            except Exception as e:
                errors.append(f"server {i}: {e}")
        if errors:
            raise MXNetError("server profiler command failed on: " +
                             "; ".join(errors))

    @property
    def prefers_batched_push(self):
        """Training glue should hand push/pull the full key list at once so
        the whole step rides one fused collective (see
        `_collective_push_batch`)."""
        return self._collective is not None

    # -- identity ------------------------------------------------------------
    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._num_workers

    # -- data plane ----------------------------------------------------------
    def _shards(self, sk, size):
        """Route a key's flat value by ELEMENT COUNT: [(server_idx,
        slice)] — one slice on one hashed server for small keys, one
        contiguous range per server above the bigarray bound."""
        n = len(self._chans)
        if n == 1 or size <= self._bigarray_bound:
            if str(sk).isdigit():
                srv = int(sk) % n
            else:
                import zlib
                srv = zlib.crc32(str(sk).encode()) % n
            return [(srv, slice(0, size))]
        bounds = [size * i // n for i in range(n + 1)]
        return [(i, slice(bounds[i], bounds[i + 1])) for i in range(n)]

    def init(self, key, value):
        """Rank 0 ships initial weights to the owning server(s); everyone
        barriers so no worker pulls before the key exists (reference
        `kvstore_dist.h` InitImpl pushes only on worker 0, then Barrier)."""
        keys, values = _normalize(key, value)
        if self._rank == 0:
            for k, v in zip(keys, values):
                sk = _key(k)
                flat = v.asnumpy().reshape(-1)
                for srv, sl in self._shards(sk, flat.size):
                    self._request(srv, {"cmd": "init", "keys": [sk],
                                        "values": [flat[sl]]})
        self._barrier()
        # keep a local copy so pull() can place results on local devices
        for k, v in zip(keys, values):
            if self._collective is not None:
                # broadcast rank 0's init over the mesh so every worker's
                # local copy is IDENTICAL (the socket path trusts each
                # worker to have initialized equally; the collective path
                # enforces it)
                import jax.numpy as jnp
                src = v._data if self._rank == 0 else \
                    jnp.zeros(v.shape, v.dtype)
                from ..ndarray.ndarray import NDArray
                summed = self._collective.allreduce(src)
                self._store[_key(k)] = NDArray(summed, ctx=self._store_ctx)
            else:
                self._store[_key(k)] = v.copyto(self._store_ctx)

    def _wire_dtype(self, merged_dtype):
        """Wire dtype for compressed-gradient collectives.  Quantized
        terms are {-t, 0, +t}; partial sums are k*t with |k| <= workers.
        bf16 (8 significand bits) keeps every k*t EXACT only when t's
        significand is a single bit (power of two) AND k <= 256 — e.g.
        t=0.3 already rounds 5t below ten workers.  Outside that envelope
        the half-width wire would silently diverge from the reference
        server path's exact accumulation, so it keeps the merged dtype."""
        import math
        import jax.numpy as jnp
        thr = float(self._compression.get("threshold", 0.5))
        frac = math.frexp(abs(thr))[0] if thr else 0.5
        if self._num_workers <= 256 and frac == 0.5:
            return jnp.bfloat16
        return merged_dtype

    def _collective_push(self, sk, vals):
        """Sync push over XLA collectives: local chip reduce, then ONE
        global all-reduce; optimizer (if shipped) applies identically on
        every worker; zero gradient bytes on the socket."""
        from ..ndarray.ndarray import NDArray
        merged = self._reduce(vals)
        if self._compression is not None:
            # error-feedback quantization BEFORE the collective: summing
            # quantized terms matches the server-side accumulate semantics.
            # The collective then rides the interconnect at HALF width —
            # quantized grads are in {-t, 0, +t} — the collective-mode
            # reading of the reference's wire compression
            # (`gradient_compression.h:52-134` saves PS bytes; this saves
            # ICI/DCN bytes).
            merged = self._compress(sk, merged)
            wire = self._wire_dtype(merged._data.dtype)
            summed = self._collective.allreduce(
                merged._data.astype(wire)).astype(merged._data.dtype)
        else:
            # allreduce returns a fresh worker-local array; wrap without
            # another device copy
            summed = self._collective.allreduce(merged._data)
        summed_nd = NDArray(summed, ctx=self._store_ctx)
        if self._updater is not None:
            self._updater(_updater_key(sk), summed_nd, self._store[sk])
        else:
            self._store[sk] = summed_nd
        self._record_key_mesh(sk, vals)

    def push(self, key, value, priority=0):
        keys, values = _normalize_push(key, value)
        if self._collective is not None:
            if len(keys) > 1:
                self._supervised(
                    "kvstore.push",
                    lambda: self._collective_push_batch(keys, values))
                return

            def _push_each():
                for k, vals in zip(keys, values):
                    sk = _key(k)
                    if sk not in self._store:
                        raise MXNetError(
                            f"Key {k} has not been initialized")
                    self._collective_push(sk, vals)
            self._supervised("kvstore.push", _push_each)
            return
        self._supervised("kvstore.push",
                         lambda: self._socket_push(keys, values))

    def _collective_push_batch(self, keys, values):
        """Batched sync push: local reduce per key, then ONE fused global
        all-reduce over the flattened bucket of every key — ~1 collective
        dispatch per training step instead of one per parameter (the
        reference batches NCCL pushes the same way, `model.py:125`)."""
        from ..ndarray.ndarray import NDArray
        import jax.numpy as jnp
        sks, merged, dtypes = [], [], []
        for k, vals in zip(keys, values):
            sk = _key(k)
            if sk not in self._store:
                raise MXNetError(f"Key {k} has not been initialized")
            m = self._reduce(vals)
            if self._compression is not None:
                # quantize + halve the wire width (see _collective_push)
                m = self._compress(sk, m)
                dtypes.append(m._data.dtype)
                merged.append(m._data.astype(
                    self._wire_dtype(m._data.dtype)))
            else:
                dtypes.append(None)
                merged.append(m._data)
            sks.append(sk)
            self._record_key_mesh(sk, vals)
        summed = self._collective.allreduce_many(merged)
        for sk, s, dt in zip(sks, summed, dtypes):
            if dt is not None:
                s = s.astype(dt)
            s_nd = NDArray(s, ctx=self._store_ctx)
            if self._updater is not None:
                self._updater(_updater_key(sk), s_nd, self._store[sk])
            else:
                self._store[sk] = s_nd

    def _socket_push(self, keys, values):
        from .compression import pack_2bit
        for k, vals in zip(keys, values):
            sk = _key(k)
            if sk not in self._store:
                raise MXNetError(f"Key {k} has not been initialized")
            merged = self._reduce(vals)      # one collective over local chips
            if self._compression is not None:
                # quantize device-side (error feedback stays on device);
                # each shard packs 4 codes/byte for its wire — 16x fewer
                # bytes than fp32 (reference gradient_compression.h)
                merged = self._compress(sk, merged)
            flat = merged.asnumpy().reshape(-1)
            for srv, sl in self._shards(sk, flat.size):
                part = flat[sl]
                if self._compression is not None:
                    wire_value = pack_2bit(part,
                                           self._compression["threshold"])
                else:
                    wire_value = part
                self._request(srv, {"cmd": "push", "key": sk,
                                    "value": wire_value,
                                    "sync": self._sync, "rank": self._rank})
                if self._sync:
                    ck = (srv, sk)
                    self._push_count[ck] = self._push_count.get(ck, 0) + 1
            self._record_key_mesh(sk, vals)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        if out is None:
            raise MXNetError("pull requires out=")
        # the sync pull is the step's rendezvous: it waits for every
        # worker's round contribution, so a dead host stalls it — run it
        # under the supervisor watchdog when one is active
        self._supervised("kvstore.pull",
                         lambda: self._pull_impl(key, out, ignore_sparse))

    def _pull_impl(self, key, out, ignore_sparse=True):
        keys, outs = _normalize_push(key, out)
        if self._collective is not None:
            # the all-reduce left an identical fresh value on every worker;
            # fan out locally, no socket round trip
            for k, tgt_list in zip(keys, outs):
                super().pull(k, out=tgt_list)
            return
        import numpy as _np
        for k, tgt_list in zip(keys, outs):
            sk = _key(k)
            src = self._store.get(sk)
            if src is None:
                # without the local shape the shard routing cannot be
                # reconstructed — and init() populates the local copy on
                # EVERY worker, so this is a protocol violation, not a
                # recoverable state
                raise MXNetError(
                    f"pull({k}): key was never initialized on this worker")
            shape = src.shape
            size = int(_np.prod(shape)) if shape else 1
            parts = []
            for srv, sl in self._shards(sk, size):
                reply = self._request(
                    srv, {"cmd": "pull", "key": sk,
                          "min_version": self._push_count.get((srv, sk), 0)})
                parts.append(_np.asarray(reply["value"]).reshape(-1))
            value = _np.concatenate(parts) if len(parts) > 1 else parts[0]
            if value.size != size:
                raise MXNetError(
                    f"pull({k}): servers returned {value.size} elements, "
                    f"local copy has {size} — worker/server shapes "
                    "disagree (inconsistent init?)")
            value = value.reshape(shape)
            src._set_data(src._data * 0 + value.astype(src.dtype))
            # local fan-out reuses the single-collective broadcast engine
            super().pull(k, out=tgt_list)

    # -- control plane -------------------------------------------------------
    def set_optimizer(self, optimizer):
        """Ship the optimizer to the server (reference pickles it through
        MXKVStoreSendCommmandToServers, `python/mxnet/kvstore.py:535`).

        Collective mode: the server never sees gradients, so the optimizer
        runs worker-side instead — every worker applies the identical
        update to the identical all-reduced gradient (the 'sharded server'
        role collapses into replicated local application; ZeRO-style
        sharded application lives in `parallel/zero.py`)."""
        self._optimizer = optimizer
        self._update_on_kvstore = True
        if self._collective is not None:
            from .. import optimizer as _opt
            self._updater = _opt.get_updater(optimizer)
            self._barrier()
            return
        if self._rank == 0:
            blob = pickle.dumps(optimizer)
            for chan in self._chans:
                _check(chan.request({"cmd": "set_optimizer",
                                     "optimizer": blob}))
        self._barrier()

    def _barrier(self):
        self._supervised(
            "kvstore.barrier",
            lambda: _check(self._chan.request({"cmd": "barrier"})))

    def close(self, send_stop=True):
        """Close every server channel.  ``send_stop=False`` skips the
        protocol 'stop' — the failover teardown path, where counting
        this worker as stopped would shut down HEALTHY servers running
        `serve_forever` out from under the restarted run."""
        from .. import profiler as _profiler
        if _profiler._kvstore_handle[0] is self:
            _profiler.set_kvstore_handle(None)
        for chan in getattr(self, "_chans", [self._chan]):
            if send_stop:
                try:
                    # best-effort, fail-fast: no reconnect/retry cycle
                    # against a server that may already be dead
                    chan.bare_request({"cmd": "stop"})
                except Exception:
                    pass
            try:
                chan.close()
            except Exception:
                pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def _check(reply):
    if "error" in reply:
        raise MXNetError(reply["error"])
    return reply


class _CollectivePlane:
    """Global all-reduce over one representative device per worker process.

    Bootstraps `jax.distributed` (dist/collective.py) and builds a 1-D
    mesh with one device column per worker; `allreduce` sums each worker's
    contribution with ONE XLA collective riding ICI/DCN (Gloo on the CPU
    test mesh).  This is the data plane the reference implements with
    range-sharded ps-lite servers (`kvstore_dist.h:44-412`) — on TPU the
    wires are the interconnect and the server keeps only control duties.
    """

    def __init__(self, rank, num_workers):
        import jax
        import numpy as np
        from . import collective
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        collective.init_process_group(num_processes=num_workers,
                                      process_id=rank)
        if jax.process_count() != num_workers:
            raise RuntimeError(
                f"jax process_count {jax.process_count()} != "
                f"num_workers {num_workers}")
        reps = []
        for p in range(num_workers):
            devs = [d for d in jax.devices() if d.process_index == p]
            if not devs:
                raise RuntimeError(f"no devices visible for process {p}")
            reps.append(devs[0])
        self._mesh = Mesh(np.array(reps), ("workers",))
        self._local_dev = reps[jax.process_index()]
        self._in_sharding = NamedSharding(self._mesh, P("workers"))
        self._out_sharding = NamedSharding(self._mesh, P())
        self._sum = jax.jit(lambda x: x.sum(axis=0),
                            out_shardings=self._out_sharding)
        self._concat_jit = {}    # signature -> flatten+concat program
        self._split_jit = {}     # signature -> split+reshape program
        # global collective dispatches issued (tests assert one per step,
        # not one per key)
        self.dispatch_count = 0

    def allreduce(self, arr):
        """Sum `arr` across all workers; returns the replicated result's
        local view (a jax array on this worker's device)."""
        import jax
        local = jax.device_put(arr, self._local_dev)[None]
        garr = jax.make_array_from_single_device_arrays(
            (self._mesh.size,) + tuple(local.shape[1:]),
            self._in_sharding, [local])
        self.dispatch_count += 1
        out = self._sum(garr)
        return [s.data for s in out.addressable_shards][0]

    def allreduce_many(self, arrs):
        """Sum a LIST of arrays across workers with ONE collective per
        dtype bucket: flatten+concat locally, all-reduce the bucket, split
        back.  The reference batches NCCL pushes the same way
        (`python/mxnet/model.py:125`); key-range splitting
        (MXNET_KVSTORE_BIGARRAY_BOUND) has no role here because there is
        no server to shard over — the interconnect carries one fused
        payload."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        if len(arrs) == 1:
            return [self.allreduce(arrs[0])]
        by_dtype = {}
        for i, a in enumerate(arrs):
            by_dtype.setdefault(np.dtype(a.dtype), []).append(i)
        out = [None] * len(arrs)
        for dt, idxs in by_dtype.items():
            group = [arrs[i] for i in idxs]
            sig = (dt,) + tuple(tuple(a.shape) for a in group)
            cat = self._concat_jit.get(sig)
            if cat is None:
                cat = jax.jit(lambda *xs: jnp.concatenate(
                    [x.reshape(-1) for x in xs]))
                self._concat_jit[sig] = cat
            local = [jax.device_put(a, self._local_dev) for a in group]
            bucket = cat(*local)
            summed = self.allreduce(bucket)
            split = self._split_jit.get(sig)
            if split is None:
                shapes = [tuple(a.shape) for a in group]
                offs = np.cumsum([0] + [int(np.prod(s)) for s in shapes])

                def _split(buf, shapes=shapes, offs=offs):
                    return tuple(
                        jax.lax.dynamic_slice_in_dim(
                            buf, int(offs[k]),
                            int(offs[k + 1] - offs[k])).reshape(shapes[k])
                        for k in range(len(shapes)))
                split = jax.jit(_split)
                self._split_jit[sig] = split
            for i, piece in zip(idxs, split(summed)):
                out[i] = piece
        return out
