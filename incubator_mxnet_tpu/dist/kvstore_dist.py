"""Worker-side distributed KVStore.

The `src/kvstore/kvstore_dist.h:44-412` role: locally reduce the per-device
gradient shards (one XLA all-reduce over the chip mesh — KVStoreTPU's
engine), then exchange ONE merged array per key with the parameter server
over the socket transport.  `dist_sync` aggregates a round across all
workers before anyone observes it; `dist_async` applies pushes immediately.

The reference encodes worker identity via the dmlc tracker env
(DMLC_RANK/DMLC_NUM_WORKER etc.); the same names are honored here so
`tools/launch.py` and existing cluster scripts port directly.
"""
from __future__ import annotations

import os
import pickle

from ..base import MXNetError
from ..kvstore import KVStoreTPU, _normalize, _normalize_push, _key
from .transport import Channel


class KVStoreDist(KVStoreTPU):
    def __init__(self, kind="dist_sync"):
        super().__init__(kind)
        self._sync = "async" not in kind
        host = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        port = int(os.environ.get("DMLC_PS_ROOT_PORT", 9091))
        self._chan = Channel(host, port)
        env_rank = os.environ.get("DMLC_RANK")
        reply = self._chan.request(
            {"cmd": "register", "role": "worker",
             "rank": int(env_rank) if env_rank is not None else None})
        self._rank = reply["rank"]
        self._num_workers = reply["num_workers"]
        self._push_count = {}    # key -> completed sync pushes by this worker
        self._update_on_kvstore = False

    # -- identity ------------------------------------------------------------
    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._num_workers

    # -- data plane ----------------------------------------------------------
    def init(self, key, value):
        """Rank 0 ships initial weights to the server; everyone barriers so
        no worker pulls before the key exists (reference `kvstore_dist.h`
        InitImpl pushes only on worker 0, then Barrier)."""
        keys, values = _normalize(key, value)
        if self._rank == 0:
            reply = self._chan.request(
                {"cmd": "init", "keys": [_key(k) for k in keys],
                 "values": [v.asnumpy() for v in values]})
            _check(reply)
        self._barrier()
        # keep a local copy so pull() can place results on local devices
        for k, v in zip(keys, values):
            self._store[_key(k)] = v.copyto(self._store_ctx)

    def push(self, key, value, priority=0):
        keys, values = _normalize_push(key, value)
        for k, vals in zip(keys, values):
            sk = _key(k)
            if sk not in self._store:
                raise MXNetError(f"Key {k} has not been initialized")
            merged = self._reduce(vals)      # one collective over local chips
            if self._compression is not None:
                # quantize device-side (error feedback stays on device),
                # then pack 4 codes/byte for the wire — 16x fewer bytes
                # than fp32 (reference gradient_compression.h packing)
                from .compression import pack_2bit
                merged = self._compress(sk, merged)
                wire_value = pack_2bit(merged.asnumpy(),
                                       self._compression["threshold"])
            else:
                wire_value = merged.asnumpy()
            reply = self._chan.request(
                {"cmd": "push", "key": sk, "value": wire_value,
                 "sync": self._sync, "rank": self._rank})
            _check(reply)
            if self._sync:
                self._push_count[sk] = self._push_count.get(sk, 0) + 1
            self._record_key_mesh(sk, vals)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        if out is None:
            raise MXNetError("pull requires out=")
        keys, outs = _normalize_push(key, out)
        for k, tgt_list in zip(keys, outs):
            sk = _key(k)
            reply = self._chan.request(
                {"cmd": "pull", "key": sk,
                 "min_version": self._push_count.get(sk, 0)})
            _check(reply)
            src = self._store.get(sk)
            if src is None or src.shape != reply["value"].shape:
                from ..ndarray.ndarray import array
                self._store[sk] = array(reply["value"], ctx=self._store_ctx)
            else:
                src._set_data(src._data * 0 + reply["value"].astype(src.dtype))
            # local fan-out reuses the single-collective broadcast engine
            super().pull(k, out=tgt_list)

    # -- control plane -------------------------------------------------------
    def set_optimizer(self, optimizer):
        """Ship the optimizer to the server (reference pickles it through
        MXKVStoreSendCommmandToServers, `python/mxnet/kvstore.py:535`)."""
        self._optimizer = optimizer
        self._update_on_kvstore = True
        if self._rank == 0:
            reply = self._chan.request(
                {"cmd": "set_optimizer",
                 "optimizer": pickle.dumps(optimizer)})
            _check(reply)
        self._barrier()

    def _barrier(self):
        _check(self._chan.request({"cmd": "barrier"}))

    def close(self):
        try:
            self._chan.request({"cmd": "stop"})
        finally:
            self._chan.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def _check(reply):
    if "error" in reply:
        raise MXNetError(reply["error"])
    return reply
