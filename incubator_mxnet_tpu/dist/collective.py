"""Multi-host collective bootstrap (`jax.distributed`).

On a real TPU pod the data plane does NOT go through the parameter server:
gradients all-reduce over ICI/DCN via XLA collectives, which is the
reference's NCCL/MPI role (`kvstore_nccl.h`, `mxnet.kvstore` dist device
modes) done the TPU way.  This module wires the process group so that
`jax.process_index()/process_count()` and cross-host `psum` work; the
sharded train step itself comes from `incubator_mxnet_tpu.parallel`.

Env: DMLC_PS_ROOT_URI/PORT double as the JAX coordinator address when
JAX_COORDINATOR_ADDRESS is unset, so one launcher config drives both the
socket control plane and the XLA data plane.

Elasticity: the group is no longer set-once.  `shutdown()` tears it down
and a later `init_process_group` re-initializes at a (possibly smaller)
world size — the shrink-and-resume path after a host loss, where the
survivors re-form the process group at the new world size before
`parallel.mesh.rebuild()` re-derives the dp mesh.  `init_process_group`
returns the ACTUAL ``(coordinator, world_size, rank)`` tuple so the
supervisor and tests can assert on what was joined, not just that
something was.
"""
from __future__ import annotations

import os

# the live group: None when no group is initialized; otherwise the
# (coordinator, world_size, rank) tuple init_process_group returned
_group = None


def init_process_group(coordinator=None, num_processes=None, process_id=None):
    """Idempotent `jax.distributed.initialize` from the dmlc-style env.

    Returns the ``(coordinator, world_size, rank)`` tuple actually joined
    (while a group is live, the EXISTING group's tuple — call `shutdown`
    first to re-init at a different world size)."""
    global _group
    if _group is not None:
        return _group
    coordinator = coordinator or os.environ.get(
        "JAX_COORDINATOR_ADDRESS",
        "%s:%s" % (os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1"),
                   int(os.environ.get("DMLC_PS_ROOT_PORT", 9091)) + 1))
    num_processes = int(num_processes if num_processes is not None
                        else os.environ.get("DMLC_NUM_WORKER", 1))
    process_id = int(process_id if process_id is not None
                     else os.environ.get("DMLC_RANK", 0))
    if num_processes <= 1:
        # single process: nothing to bootstrap, but identity is still real
        _group = (coordinator, 1, 0)
        return _group
    import jax
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    _group = (coordinator, num_processes, process_id)
    return _group


def initialized():
    """Whether a process group is currently live."""
    return _group is not None


def group():
    """The live group's (coordinator, world_size, rank), or None."""
    return _group


def shutdown():
    """Tear the process group down so a new one can form — the epoch
    boundary of shrink-and-resume (survivors re-init at the smaller world
    size, typically against an epoch-specific coordinator port)."""
    global _group
    if _group is None:
        return
    if _group[1] > 1:
        import jax
        try:
            jax.distributed.shutdown()
        except Exception:
            pass
    _group = None


# historical name (pre-elastic); shutdown() is the re-init-capable spelling
finalize = shutdown
