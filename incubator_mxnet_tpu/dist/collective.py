"""Multi-host collective bootstrap (`jax.distributed`).

On a real TPU pod the data plane does NOT go through the parameter server:
gradients all-reduce over ICI/DCN via XLA collectives, which is the
reference's NCCL/MPI role (`kvstore_nccl.h`, `mxnet.kvstore` dist device
modes) done the TPU way.  This module wires the process group so that
`jax.process_index()/process_count()` and cross-host `psum` work; the
sharded train step itself comes from `incubator_mxnet_tpu.parallel`.

Env: DMLC_PS_ROOT_URI/PORT double as the JAX coordinator address when
JAX_COORDINATOR_ADDRESS is unset, so one launcher config drives both the
socket control plane and the XLA data plane.
"""
from __future__ import annotations

import os

_initialized = False


def init_process_group(coordinator=None, num_processes=None, process_id=None):
    """Idempotent `jax.distributed.initialize` from the dmlc-style env."""
    global _initialized
    if _initialized:
        return True
    import jax
    coordinator = coordinator or os.environ.get(
        "JAX_COORDINATOR_ADDRESS",
        "%s:%s" % (os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1"),
                   int(os.environ.get("DMLC_PS_ROOT_PORT", 9091)) + 1))
    num_processes = int(num_processes if num_processes is not None
                        else os.environ.get("DMLC_NUM_WORKER", 1))
    process_id = int(process_id if process_id is not None
                     else os.environ.get("DMLC_RANK", 0))
    if num_processes <= 1:
        _initialized = True
        return True
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True
    return True


def finalize():
    global _initialized
    if not _initialized:
        return
    import jax
    try:
        jax.distributed.shutdown()
    except Exception:
        pass
    _initialized = False
