"""Coordinator-side membership for the elastic multi-host supervisor.

On a TPU pod the failure that matters is not a dead parameter server but a
dead HOST: every surviving host then blocks inside an XLA collective with
no error and no timeout.  The first requirement for converting that hang
into a recoverable event is an authoritative answer to "who is still
here?" — this module is that answer, hosted by the root parameter server
(the process the workers already hold a control channel to) and driven by
`resilience.supervisor.JobSupervisor` heartbeats riding the existing
sequence-numbered `dist.transport` frames.

Three pieces:

* **liveness** — every host heartbeats (`hb` frames) with its membership
  epoch, step counter, and step-time EWMA; a host whose last heartbeat is
  older than ``deadline_s`` is *dead* in every subsequent view.  The
  judgement is breaker-like (consecutive silence trips it) but keyed on
  wall silence rather than failures: a heartbeat is its own probe.

* **epoch fencing** — the membership epoch bumps at every shrink commit.
  A heartbeat, shrink proposal, or (via `dist.server`) worker
  registration carrying a stale epoch is REJECTED: a host that missed a
  shrink (partitioned, wedged in a collective) cannot rejoin the pod and
  corrupt post-shrink state.  This is the TensorFlow-supervisor fencing
  token design (PAPERS.md) on the ps-lite control plane.

* **shrink barrier** — on confirmed host loss, every survivor proposes a
  shrink.  The barrier commits when every host still alive has proposed;
  at the deadline it commits with whoever arrived ONLY when the
  proposers form a strict majority of the hosts still alive — one host
  with a misfiring watchdog must not be able to shrink a healthy pod
  down to itself (its proposal fails instead, and it alone dies).  The
  commit bumps the epoch, densely re-ranks the survivors (old rank ->
  new rank, sorted order) and hands the server an ``on_commit`` callback
  to reset kvstore state for the new world.  Proposals for an
  already-committed epoch replay the committed result (idempotent: a
  resent proposal must not re-shrink).
"""
from __future__ import annotations

import time

from ..analysis import locks as _locks
from ..analysis import tsan as _tsan

__all__ = ["MembershipTable"]


class _Host:
    __slots__ = ("rank", "last", "step", "ewma", "beats", "label")

    def __init__(self, rank, now):
        self.rank = rank
        self.last = now       # monotonic time of the last heartbeat
        self.step = 0
        self.ewma = None      # step-time EWMA reported by the host
        self.beats = 0
        self.label = None     # human name (serving-fleet host ids)


class MembershipTable:
    """Per-pod membership: liveness view, epoch fence, shrink barrier.

    Thread-safe; the clock is injectable so death/deadline sequences are
    testable without sleeping (the `CircuitBreaker` convention).
    """

    def __init__(self, num_workers, deadline_s, clock=time.monotonic):
        self.deadline_s = float(deadline_s)
        self.expected = int(num_workers)   # current world size
        self.epoch = 0
        self._clock = clock
        self._cond = _locks.make_condition(name="dist.membership")
        # rank -> _Host; server handler threads (one per connection)
        # all mutate it — every access holds _cond's lock, and the
        # sanitizer checks exactly that when MXNET_TSAN=1
        self._hosts = _tsan.shared_dict("dist.membership.hosts")
        self._shrink = None                # in-flight barrier state
        self._last_shrink = None           # committed result (replayed)
        _tsan.instrument(self, "dist.membership")

    # -- liveness -------------------------------------------------------------
    def heartbeat(self, rank, epoch, step=None, step_time=None,
                  label=None):
        """One host heartbeat.  Returns the membership view, or an
        ``{"error": ...}`` dict when the host's epoch is stale (the fence:
        it must not be allowed to keep participating).  ``label`` is an
        optional human name carried into the view (the serving fleet
        beats by registry rank but reports by host id)."""
        with self._cond:
            fence = self._fence(rank, epoch, "heartbeat")
            if fence is not None:
                return fence
            now = self._clock()
            rec = self._hosts.get(rank)
            if rec is None:
                rec = self._hosts[rank] = _Host(int(rank), now)
            rec.last = now
            rec.beats += 1
            if step is not None:
                rec.step = int(step)
            if step_time is not None:
                rec.ewma = float(step_time)
            if label is not None:
                rec.label = str(label)
            self._cond.notify_all()
            return {"ok": True, "view": self._view_locked()}

    def view(self):
        """The current membership view without heartbeating."""
        with self._cond:
            return self._view_locked()

    def check_epoch(self, epoch):
        """Fence check for non-membership commands (`register`): None when
        current, an error dict naming the stale epoch otherwise."""
        with self._cond:
            return self._fence(None, epoch, "request")

    def _fence(self, rank, epoch, what):
        if int(epoch) == self.epoch:
            return None
        who = f"host {rank} " if rank is not None else ""
        return {"error": f"stale epoch: {who}{what} carries membership "
                         f"epoch {int(epoch)} but the pod is at epoch "
                         f"{self.epoch} — this host missed a shrink and is "
                         "fenced out (it must not rejoin; restart it "
                         "against the current epoch)"}

    def _view_locked(self):
        now = self._clock()
        alive, dead, ages = [], [], {}
        for rank, rec in sorted(self._hosts.items()):
            age = now - rec.last
            ages[rank] = round(age, 3)
            (dead if age > self.deadline_s else alive).append(rank)
        return {"epoch": self.epoch,
                "world_size": self.expected,
                "alive": alive,
                "dead": dead,
                "age": ages,
                "steps": {r: self._hosts[r].step for r in self._hosts},
                "ewma": {r: self._hosts[r].ewma for r in self._hosts
                         if self._hosts[r].ewma is not None},
                "labels": {r: self._hosts[r].label for r in self._hosts
                           if self._hosts[r].label is not None}}

    # -- shrink barrier -------------------------------------------------------
    def propose_shrink(self, rank, epoch, deadline_s, on_commit=None):
        """Epoch-fenced barrier-with-deadline.  Blocks until every host
        still alive has proposed (or ``deadline_s`` passes), then commits:
        epoch += 1, survivors = the proposers, dense re-rank.  Returns the
        committed result dict (identical for every proposer), including
        this proposer's ``rank_map``.  A proposal for the epoch that was
        JUST committed replays the result (idempotent resends)."""
        rank = int(rank)
        with self._cond:
            if int(epoch) == self.epoch - 1 and self._last_shrink is not None:
                # resent / late proposal for the committed shrink: replay
                # the result IF this host made the survivor cut — a host
                # that missed the barrier is fenced, not readmitted
                if rank in self._last_shrink["survivors"]:
                    return dict(self._last_shrink)
            fence = self._fence(rank, epoch, "shrink proposal")
            if fence is not None:
                return fence
            if self._shrink is None or self._shrink["epoch"] != self.epoch:
                self._shrink = {"epoch": self.epoch, "proposed": set(),
                                "t_end": self._clock() + float(deadline_s)}
            sh = self._shrink
            sh["proposed"].add(rank)
            # proposing proves liveness (the proposer may have spent its
            # heartbeat budget blocked in the hung collective)
            rec = self._hosts.get(rank)
            if rec is not None:
                rec.last = self._clock()
            self._cond.notify_all()
            while True:
                # a commit NEWER than this barrier's start epoch is THIS
                # barrier's commit (the epoch can only have advanced
                # through it) — every co-proposer replays it.  Comparing
                # against the CURRENT epoch would wrongly replay a
                # previous shrink's result on the next host loss.
                if self._last_shrink is not None and \
                        self._last_shrink["epoch"] > sh["epoch"]:
                    return dict(self._last_shrink)
                if self._shrink is not sh:
                    # another proposer aborted this barrier (no quorum)
                    return {"error": "shrink barrier aborted without a "
                                     "quorum — the pod majority is "
                                     "healthy; refusing to shrink"}
                view = self._view_locked()
                waiting_on = [r for r in view["alive"]
                              if r not in sh["proposed"]]
                if not waiting_on and \
                        len(sh["proposed"]) * 2 > self.expected:
                    # everyone still alive has proposed AND the proposers
                    # are a strict majority of the current world: commit
                    # early.  Without the majority clause, a healthy
                    # survivor whose heartbeats lapsed during its own
                    # teardown (stopped supervisor + long checkpoint
                    # flush BEFORE proposing) would be counted dead and
                    # fenced out by the first proposer; a sub-majority
                    # waits for it until the deadline instead.
                    return self._commit_locked(sh, on_commit)
                if self._clock() >= sh["t_end"]:
                    # deadline with live non-proposers: commit only on a
                    # strict proposer majority of everyone still alive —
                    # a single host whose watchdog misfired must not be
                    # able to shrink a healthy pod down to itself
                    alive = set(view["alive"]) | sh["proposed"]
                    if len(sh["proposed"]) * 2 > len(alive):
                        return self._commit_locked(sh, on_commit)
                    self._shrink = None
                    self._cond.notify_all()
                    return {"error": "shrink barrier timed out without a "
                                     f"quorum: {sorted(sh['proposed'])} "
                                     f"proposed but {sorted(alive)} are "
                                     "alive — the pod majority is healthy; "
                                     "refusing to shrink (check this "
                                     "host's collective/watchdog "
                                     "deadlines)"}
                # wake periodically: the alive set shrinks as deadlines
                # pass, with no event to signal it
                self._cond.wait(timeout=min(
                    0.05, max(sh["t_end"] - self._clock(), 0.0) + 0.01))

    def _commit_locked(self, sh, on_commit):
        survivors = sorted(sh["proposed"])
        self.epoch += 1
        self.expected = len(survivors)
        result = {"ok": True, "epoch": self.epoch,
                  "world_size": len(survivors),
                  "survivors": survivors,
                  "rank_map": {old: new for new, old in enumerate(survivors)},
                  "epoch_committed": self.epoch}
        # the new epoch starts with a clean slate: survivors re-register
        # and re-heartbeat under their NEW ranks; stale records must not
        # shadow them
        self._hosts.clear()
        if self._shrink is sh:
            self._shrink = None
        self._last_shrink = {**result, "epoch": self.epoch}
        if on_commit is not None:
            on_commit(result)
        self._cond.notify_all()
        return dict(result)
